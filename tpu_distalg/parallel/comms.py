"""Communication-efficient collectives — the instrumented comms layer.

The reference's entire aggregation story is Spark's ``treeAggregate`` +
``broadcast``; our original replacement was a naive per-leaf ``lax.psum``
(``collectives.tree_allreduce_sum``) — full-precision, unbucketed,
unoverlapped gradient traffic on every sync round of every SGD-family
trainer. This module is the single choke point that traffic now routes
through: a :class:`CommSpec`-driven schedule selected per run, with
per-sync wire-byte accounting so the artifact can finally say how many
bytes a trainer moved.

Schedules (all deterministic and bitwise-replayable — fixed reduction
order, counter-based PRNG only):

  ``dense``     today's fused psum per leaf, bitwise-identical to
                ``tree_allreduce_sum`` — the default.
  ``bucketed``  the pytree is flattened into fixed-size buckets; each
                bucket is reduced by a ``ppermute``-chunk ring
                (reduce-scatter + all-gather, the ``ring.py``
                ``fori_loop`` idiom), scanned bucket-by-bucket so the
                collective of bucket *b* overlaps the unpacking compute
                of bucket *b−1* (cf. the chunked, topology-aware
                schedules of arXiv:2112.01075).
  ``hier``      hierarchical: ring reduce-scatter INSIDE each group
                (the intra-host/ICI axis), a cross-group ring of the
                owned chunk (the DCN axis — 1/m of the payload crosses
                the slow links), then an intra-group all-gather.
                Groups come from the mesh's hybrid layout
                (``slice_index``/``process_index`` of the data-axis
                devices) or from ``hier_groups``.
  ``bf16``      cast to bfloat16 on the wire, one psum, cast back —
                half the bytes, the standard gradient-compression
                baseline (a bf16 psum really moves bf16).
  ``int8``      the NATIVE compressed ring (round 11 closed PR 5's
                int32-psum caveat): per bucket, seeded STOCHASTIC
                rounding to int8 against a pmax-shared scale, an
                ``all_to_all`` chunk scatter that puts int8 on the
                wire, EXACT int32 accumulation of the integer
                contributions at the chunk owner (order-free, so
                deterministic for free), a second seeded stochastic
                requantization of the reduced chunk (scale ``n·s`` —
                the integer sum is bounded by ``127·n``), and an int8
                ppermute ring all-gather. Both phases move int8, so
                the ~4x wire reduction is ON the wire, not in the
                accounting. Unbiased in expectation,
                bitwise-replayable: rounding noise is
                threefry(seed, step, shard, bucket·stage).
  ``topk``      top-k sparsification with ERROR FEEDBACK: each shard
                keeps the k largest-|.| entries of (gradient +
                residual), combines only those via
                :func:`sparse_allreduce` (the generalized ring
                all-gather of (value, index) pairs), and carries the
                unsent remainder in the scan state so nothing is ever
                lost — the sparse-allreduce construction of
                arXiv:1312.3020 with the EF-SGD residual correction
                that preserves convergence.

Overlap (round 11): the bucketed flat-vector schedules (``bucketed``,
``int8``) run their buckets through a DOUBLE-BUFFERED software
pipeline — the collective chain of bucket *b* is launched while bucket
*b−1*'s unpack/dequantize compute finishes, so XLA's latency-hiding
scheduler can hide the wire time behind the math instead of running
them back to back (cf. the chunked, portable collective schedules of
arXiv:2112.01075). On by default; spell ``<schedule>@seq`` to force the
sequential exchange (the pipeline and the sequential loop are
bitwise-identical — same per-bucket math, different interleaving — so
``@seq`` exists for A/B timing, not for correctness). ``hier`` rides
the same code path but always as ONE bucket, and ``topk``'s pair
exchange is its own single in-flight buffer — ``@seq`` is accepted on
both and is a no-op by construction. ``reduce`` also
takes a ``compute=`` thunk of trainer math that is independent of the
sync (e.g. the regularization gradient); it is evaluated next to the
first in-flight bucket so the scheduler can hide the exchange behind
it. The pipeline drains inside every sync, so the only cross-step comm
state remains the error-feedback residual — which rides the scan carry
and the checkpoint exactly as before (a resume mid-schedule is bitwise).

Compression applies to float leaves with more than one element; scalars
and integer leaves (step counts, minibatch counts) always go dense — a
compressed count would corrupt the update denominators for no
measurable byte win.

Byte accounting (:meth:`CommSync.stats`): ``bytes_wire`` is the
per-shard payload that crosses the interconnect per sync under a
bandwidth-optimal ring at the schedule's wire precision
(``2·B·(n−1)/n`` for an allreduce of B bytes); ``bytes_logical`` is the
f32 payload the sync logically reduces. Trainers multiply by the sync
count and bump the ``comm.bytes_wire`` / ``comm.bytes_logical`` /
``comm.rounds`` telemetry counters, so ``tda report`` shows the
compression ratio actually achieved.
"""

from __future__ import annotations

import dataclasses
import math

import numpy as np

#: mirror of ``parallel.mesh.DATA_AXIS`` — deliberately NOT imported:
#: mesh.py imports jax at module level, and the cluster tier's
#: jax-free host processes (coordinator, transport-only tools) import
#: this module for the HOST-SIDE CODECS below; the device schedules
#: keep importing jax lazily inside their functions as before
DATA_AXIS = "data"

SCHEDULES = ("dense", "bucketed", "hier", "bf16", "int8", "topk")

#: float leaves with more elements than this are compressed; at or
#: below it (and for every integer leaf) the schedule falls back to a
#: dense psum — the (grad, count) pairs every trainer syncs keep their
#: count exact.
MIN_COMPRESS_ELEMS = 1


def psum(x, axis_name: str = DATA_AXIS):
    """The blessed raw psum — same op as ``lax.psum``, imported from
    the comms layer so ``tda lint`` (TDA050) can keep every cross-shard
    reduction in ``models/`` behind this instrumentable choke point."""
    from jax import lax

    return lax.psum(x, axis_name)


def pmean(x, axis_name: str = DATA_AXIS):
    """Blessed raw pmean (see :func:`psum`)."""
    from jax import lax

    return lax.pmean(x, axis_name)


def pmax(x, axis_name: str = DATA_AXIS):
    """Blessed raw pmax (see :func:`psum`)."""
    from jax import lax

    return lax.pmax(x, axis_name)


def pmin(x, axis_name: str = DATA_AXIS):
    """Blessed raw pmin (see :func:`psum`)."""
    from jax import lax

    return lax.pmin(x, axis_name)


@dataclasses.dataclass(frozen=True)
class CommSpec:
    """One run's aggregation schedule + knobs.

    ``parse`` accepts the CLI spelling: a schedule name with an
    optional ``:arg`` — ``topk:0.01`` (kept fraction), ``bucketed:65536``
    (elements per bucket), ``hier:2`` (group count; 0 = infer from the
    mesh topology), ``int8:7`` (stochastic-rounding seed;
    ``int8:7:4096`` also sets the overlap-bucket element count) — plus
    an optional ``@seq`` suffix that disables the double-buffered
    bucket-overlap pipeline (``int8@seq``, ``topk:0.05@seq``).
    Overlapped and sequential schedules are bitwise-identical; ``@seq``
    is the A/B-timing spelling.
    """

    schedule: str = "dense"
    bucket_elems: int = 1 << 16      # 'bucketed'/'int8': elems/bucket
    topk_fraction: float = 0.01      # 'topk': fraction of entries kept
    hier_groups: int = 0             # 'hier': 0 = infer from topology
    seed: int = 0                    # 'int8': stochastic-rounding seed
    overlap: bool = True             # double-buffered bucket pipeline

    def __post_init__(self):
        if self.schedule not in SCHEDULES:
            raise ValueError(
                f"unknown comm schedule {self.schedule!r}; want one of "
                f"{', '.join(SCHEDULES)}")
        if not (0.0 < self.topk_fraction <= 1.0):
            raise ValueError(
                f"topk_fraction must be in (0, 1], got "
                f"{self.topk_fraction}")
        if self.bucket_elems < 1:
            raise ValueError(
                f"bucket_elems must be >= 1, got {self.bucket_elems}")

    @classmethod
    def parse(cls, text: str | "CommSpec" | None) -> "CommSpec":
        if isinstance(text, cls):
            return text
        if not text:
            return cls()
        text = str(text)
        kw = {}
        if text.endswith("@seq"):
            text, kw["overlap"] = text[: -len("@seq")], False
        elif text.endswith("@ov"):
            text = text[: -len("@ov")]  # explicit spelling of default
        name, _, arg = text.partition(":")
        if arg:
            if name == "topk":
                kw["topk_fraction"] = float(arg)
            elif name == "bucketed":
                kw["bucket_elems"] = int(arg)
            elif name == "hier":
                kw["hier_groups"] = int(arg)
            elif name == "int8":
                seed, _, bucket = arg.partition(":")
                kw["seed"] = int(seed)
                if bucket:
                    kw["bucket_elems"] = int(bucket)
            else:
                raise ValueError(
                    f"comm schedule {name!r} takes no argument "
                    f"(got {text!r})")
        return cls(schedule=name, **kw)

    @property
    def stateful(self) -> bool:
        """Whether the schedule carries error-feedback residuals."""
        return self.schedule == "topk"


def infer_groups(mesh, axis_name: str = DATA_AXIS) -> int:
    """Group count for the hierarchical schedule, off the mesh's hybrid
    layout: the number of distinct slices (TPU multi-slice DCN
    boundary) or host processes among the data-axis devices. Falls back
    to 2 when the topology is flat but even (so CPU-emulated meshes
    still exercise both levels), else 1 (plain ring)."""
    axis = list(mesh.axis_names).index(axis_name)
    n = mesh.devices.shape[axis]
    # one representative device per data-axis coordinate
    devs = np.moveaxis(mesh.devices, axis, 0).reshape(n, -1)[:, 0]
    for attr in ("slice_index", "process_index"):
        marks = [getattr(d, attr, 0) or 0 for d in devs]
        g = len(set(marks))
        if 1 < g < n and n % g == 0:
            return g
    return 2 if n % 2 == 0 and n > 2 else 1


def _eligible(leaf) -> bool:
    """Compressible: a float leaf with more than MIN_COMPRESS_ELEMS
    elements (works on arrays and ShapeDtypeStructs)."""
    dt = np.dtype(leaf.dtype)
    return (dt.kind == "f"
            and int(np.prod(leaf.shape)) > MIN_COMPRESS_ELEMS)


def _ring_perm(n: int):
    return [(i, (i + 1) % n) for i in range(n)]


def _ring_allgather(buf, axis_name: str, n: int):
    """Origin-placed ring all-gather of one per-shard buffer (or a
    pytree of them): ``n−1`` ``ppermute`` hops of ``buf``-sized
    messages (the wire carries each leaf's own dtype); each leaf comes
    back ``(n, *leaf.shape)`` with row *j* = shard *j*'s buffer,
    bitwise-identical on every shard. All leaves hop inside the SAME
    fori_loop, so a pair exchange (topk's value+index buffers) pays
    ``n−1`` hop latencies, not ``2(n−1)`` back-to-back loops. The ONE
    ring-gather implementation — the sparse pair exchange and the
    native int8 ring both ride it, so a hop-ordering fix can never
    land in one and not the other (the bug class PR 5's review caught
    in the hier schedule)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    my = lax.axis_index(axis_name)
    perm = _ring_perm(n)
    acc0 = jax.tree.map(
        lambda b: lax.dynamic_update_index_in_dim(
            jnp.zeros((n,) + b.shape, b.dtype), b, my, 0), buf)

    def hop(s, carry):
        b, acc = carry
        b = jax.tree.map(
            lambda x: lax.ppermute(x, axis_name, perm), b)
        src = (my - s - 1) % n
        acc = jax.tree.map(
            lambda a, x: lax.dynamic_update_index_in_dim(a, x, src, 0),
            acc, b)
        return b, acc

    _, acc = lax.fori_loop(0, n - 1, hop, (buf, acc0))
    return acc


#: public name for the origin-placed ring all-gather: the serving
#: layer's sharded top-k candidate merge rides the SAME pair exchange
#: the topk gradient schedule and sparse_allreduce do (each shard
#: contributes its k (value, index) pairs — ``8k(n−1)`` wire bytes per
#: sync instead of an O(length) dense gather), so a hop-ordering fix
#: can never land in one rider and not another
ring_allgather = _ring_allgather


def _ring_allreduce(v, axis_name: str, n: int):
    """Bandwidth-optimal ring allreduce of a flat ``(n·chunk,)`` f32
    vector: n−1 reduce-scatter steps then n−1 all-gather steps, all
    ``ppermute`` chunk rotations (the ``ring.py`` fori_loop idiom).
    Deterministic: the accumulation order around the ring is fixed."""
    import jax.numpy as jnp
    from jax import lax

    if n == 1:
        return v
    my = lax.axis_index(axis_name)
    chunk = v.shape[0] // n
    blocks = v.reshape(n, chunk)
    perm = _ring_perm(n)

    # reduce-scatter: at step s shard i sends its partial of block
    # (i − s) mod n and accumulates the arriving partial of block
    # (i − s − 1) mod n; after n−1 steps shard i owns the fully
    # reduced block (i + 1) mod n
    def rs(s, blocks):
        send_id = (my - s) % n
        buf = lax.dynamic_index_in_dim(blocks, send_id, keepdims=False)
        buf = lax.ppermute(buf, axis_name, perm)
        recv_id = (my - s - 1) % n
        old = lax.dynamic_index_in_dim(blocks, recv_id, keepdims=False)
        return lax.dynamic_update_index_in_dim(
            blocks, old + buf, recv_id, 0)

    blocks = lax.fori_loop(0, n - 1, rs, blocks)

    # all-gather: rotate the finished blocks around the ring; at step s
    # shard i holds (and forwards) the reduced block owned by shard
    # (i − s) mod n, i.e. block (i − s + 1) mod n
    own_id = (my + 1) % n
    out0 = lax.dynamic_update_index_in_dim(
        jnp.zeros_like(blocks),
        lax.dynamic_index_in_dim(blocks, own_id, keepdims=False),
        own_id, 0)

    def ag(s, carry):
        buf, out = carry
        buf = lax.ppermute(buf, axis_name, perm)
        blk_id = (my - s) % n  # arrived from shard (i−s−1): its block
        out = lax.dynamic_update_index_in_dim(out, buf, blk_id, 0)
        return buf, out

    buf0 = lax.dynamic_index_in_dim(blocks, own_id, keepdims=False)
    _, out = lax.fori_loop(0, n - 1, ag, (buf0, out0))
    return out.reshape(-1)


def _hier_allreduce(v, axis_name: str, n: int, g: int):
    """Two-level allreduce of a flat ``(m·chunk,)`` vector over ``g``
    groups of ``m = n/g`` shards: intra-group ring reduce-scatter (the
    fast/ICI links carry the full payload), a cross-group ring of the
    owned chunk (only 1/m of the payload crosses the slow/DCN links),
    then an intra-group all-gather."""
    import jax.numpy as jnp
    from jax import lax

    m = n // g
    if m == 1 or g == 1:
        # no intra-group phase: the caller padded v to a multiple of n
        # for exactly this flat-ring fallback
        return _ring_allreduce(v, axis_name, n)
    my = lax.axis_index(axis_name)
    grp, loc = my // m, my % m
    chunk = v.shape[0] // m
    blocks = v.reshape(m, chunk)
    # intra-group ring: i → (same group, local+1)
    perm_in = [(G * m + L, G * m + (L + 1) % m)
               for G in range(g) for L in range(m)]
    # cross-group ring between same-local shards: i → (group+1, local)
    perm_x = [(G * m + L, ((G + 1) % g) * m + L)
              for G in range(g) for L in range(m)]

    def rs(s, blocks):
        send_id = (loc - s) % m
        buf = lax.dynamic_index_in_dim(blocks, send_id, keepdims=False)
        buf = lax.ppermute(buf, axis_name, perm_in)
        recv_id = (loc - s - 1) % m
        old = lax.dynamic_index_in_dim(blocks, recv_id, keepdims=False)
        return lax.dynamic_update_index_in_dim(
            blocks, old + buf, recv_id, 0)

    blocks = lax.fori_loop(0, m - 1, rs, blocks)
    own_id = (loc + 1) % m
    own = lax.dynamic_index_in_dim(blocks, own_id, keepdims=False)

    # cross-group all-gather of the owned chunk, then ORIGIN-ORDER
    # accumulation (group 0 first): every shard with the same local
    # index owns the SAME block id, and summing the g group-partials
    # in a fixed order keeps the result bitwise-identical on every
    # shard — an accumulate-and-forward would sum in each group's own
    # rotational order and silently de-replicate the output for g >= 3
    # (float addition is not associative; same reason the topk path
    # gathers before accumulating)
    all_c = lax.dynamic_update_index_in_dim(
        jnp.zeros((g,) + own.shape, own.dtype), own, grp, 0)

    def xg(s, carry):
        buf, all_c = carry
        buf = lax.ppermute(buf, axis_name, perm_x)
        src = (grp - s - 1) % g
        all_c = lax.dynamic_update_index_in_dim(all_c, buf, src, 0)
        return buf, all_c

    _, all_c = lax.fori_loop(0, g - 1, xg, (own, all_c))
    own = lax.fori_loop(
        0, g, lambda j, acc: acc + all_c[j], jnp.zeros_like(own))

    # intra-group all-gather of the m finished blocks
    out0 = lax.dynamic_update_index_in_dim(
        jnp.zeros_like(blocks), own, own_id, 0)

    def ag(s, carry):
        buf, out = carry
        buf = lax.ppermute(buf, axis_name, perm_in)
        blk_id = (loc - s) % m
        out = lax.dynamic_update_index_in_dim(out, buf, blk_id, 0)
        return buf, out

    _, out = lax.fori_loop(0, m - 1, ag, (own, out0))
    return out.reshape(-1)


def sparse_allreduce(vals, idx, length: int, *,
                     axis_name: str = DATA_AXIS, n: int | None = None):
    """Sparse-vector allreduce: every shard contributes ``k`` (value,
    index) pairs; returns the dense ``(length,)`` f32 sum, replicated
    bitwise-identically on every shard.

    The exchange is a ring all-gather of the pair buffers — ``n−1``
    ``ppermute`` hops of ``8k`` bytes each, so the bytes crossing the
    interconnect are exactly the sparse payload (a psum of a
    zero-padded dense vector would move full-length f32). Every shard
    then scatter-accumulates the ``n`` contributions in ORIGIN order
    (shard 0 first): float addition is not associative, and per-shard
    arrival order would silently de-replicate the result — this is the
    replicated-output contract psum gives for free, earned without
    psum.

    Generalized out of the top-k gradient schedule (PR 5) so any sparse
    combine can ride it — e.g. power-law rank deltas in graph workloads
    (arXiv:1312.3020 is explicitly about power-law data). Duplicate
    indices within one shard's contribution accumulate additively.
    """
    import jax.numpy as jnp
    from jax import lax

    if n is None:
        from tpu_distalg.parallel.compat import axis_size

        n = axis_size(axis_name)
    if n == 1:
        return jnp.zeros((length,), vals.dtype).at[idx].add(vals)
    all_v, all_i = _ring_allgather((vals, idx), axis_name, n)
    return lax.fori_loop(
        0, n,
        lambda j, out: out.at[all_i[j]].add(all_v[j]),
        jnp.zeros((length,), vals.dtype))


def _pipelined_buckets(buckets, exchange, finish, overlap: bool,
                       compute=None):
    """Run ``finish(exchange(bucket_i, i))`` over every bucket.

    ``overlap=True`` is the double-buffered schedule: the scan carry
    holds the in-flight (exchanged-but-unfinished) bucket, so iteration
    *i* launches bucket *i*'s collective chain with no data dependence
    on bucket *i−1*'s ``finish`` compute — XLA's latency-hiding
    scheduler overlaps the two. ``overlap=False`` chains them
    (exchange → finish per bucket). Both orders evaluate the identical
    per-bucket composition, so the outputs are BITWISE equal — the
    pipeline buys wall-clock, never numerics. ``compute`` (optional
    thunk of sync-independent caller math) is evaluated next to the
    first in-flight bucket and its result returned alongside, giving
    the scheduler trainer compute to hide the first exchange behind.
    Returns ``(stacked_outputs, aux)``.
    """
    import jax.numpy as jnp
    from jax import lax

    nb = buckets.shape[0]
    idx = jnp.arange(nb)
    if not overlap:
        aux = compute() if compute is not None else None

        def one(_, x):
            b, i = x
            return None, finish(exchange(b, i))

        _, out = lax.scan(one, None, (buckets, idx))
        return out, aux

    inflight = exchange(buckets[0], idx[0])
    # evaluated AFTER the first exchange is in flight and independent
    # of it — the scheduler may run it under the collective's latency
    aux = compute() if compute is not None else None

    def one(inflight, x):
        b, i = x
        nxt = exchange(b, i)        # bucket i's collective chain ...
        out = finish(inflight)      # ... overlaps bucket i−1's unpack
        return nxt, out

    last, head = lax.scan(one, inflight, (buckets[1:], idx[1:]))
    tail = finish(last)
    return jnp.concatenate([head, tail[None]], axis=0), aux


class CommSync:
    """One sync point's compiled-in schedule: built once per trainer
    from the spec, the mesh and an example pytree (shapes/dtypes), then
    called INSIDE the shard_map body every sync round.

    ``reduce(tree, res, t)`` returns ``(summed_tree, res_new)`` where
    ``res`` is the flat error-feedback residual — shape ``(1, ef_elems)``
    inside the body (the caller shards the ``(n_shards, ef_elems)``
    state over the data axis, exactly like per-replica models), or
    ``None`` for stateless schedules. ``t`` is the absolute sync/step id
    — the int8 stochastic-rounding key folds it in, so segmented
    checkpoint/resume replays identical rounding noise.
    """

    def __init__(self, spec: CommSpec, mesh, example, *,
                 axis_name: str = DATA_AXIS):
        import jax

        self.spec = spec
        self.axis_name = axis_name
        self.n_shards = int(mesh.shape[axis_name])
        self.groups = (spec.hier_groups
                       or infer_groups(mesh, axis_name))
        if self.spec.schedule == "hier" and self.n_shards % self.groups:
            raise ValueError(
                f"hier: {self.groups} groups do not divide the "
                f"'{axis_name}' axis size {self.n_shards}")
        leaves = jax.tree.leaves(example)
        self._eligible_mask = [_eligible(x) for x in leaves]
        self._sizes = [int(np.prod(x.shape)) for x in leaves]
        self.ef_elems = sum(
            s for s, e in zip(self._sizes, self._eligible_mask) if e)

    # ---------------------------------------------------------- state

    @property
    def stateful(self) -> bool:
        return self.spec.stateful and self.ef_elems > 0

    def init_state(self):
        """Host-side zero residual, ``(n_shards, ef_elems)`` — shard it
        ``P(axis, None)`` and thread it through the trainer's scan
        carry. Zero-WIDTH (``(n_shards, 0)``) for stateless schedules,
        so callers keep one uniform carry/checkpoint layout per comm
        run instead of a stateful/stateless fork."""
        width = self.ef_elems if self.stateful else 0
        return np.zeros((self.n_shards, width), np.float32)

    # ------------------------------------------------------- schedule

    def reduce(self, tree, res=None, t=0, compute=None):
        """Allreduce-SUM ``tree`` across the axis under the schedule.
        Returns ``(tree_summed, res_new)``; ``res_new`` is ``None``
        exactly when :attr:`stateful` is false.

        ``compute`` (optional zero-arg thunk of caller math that is
        INDEPENDENT of the sync — e.g. the regularization gradient) is
        evaluated next to the first in-flight bucket of the overlap
        pipeline so the scheduler can hide the exchange behind it; its
        result is returned as a third element:
        ``(tree_summed, res_new, aux)``."""
        import jax

        if self.spec.schedule == "dense" or self.n_shards == 1:
            from jax import lax

            out = jax.tree.map(
                lambda x: lax.psum(x, self.axis_name), tree)
            if compute is None:
                return out, res
            return out, res, compute()
        return self._reduce_split(tree, res, t, compute)

    def reduce_mean(self, tree, res=None, t=0, compute=None):
        """Allreduce-MEAN: ``dense`` uses ``lax.pmean`` (bitwise-equal
        to ``tree_allreduce_mean``); compressed schedules sum then
        divide. Error feedback is applied to the MEAN's deviation, so
        the topk residual correction carries the right scale.
        ``compute`` as in :meth:`reduce`."""
        import jax

        if self.spec.schedule == "dense" or self.n_shards == 1:
            from jax import lax

            out = jax.tree.map(
                lambda x: lax.pmean(x, self.axis_name), tree)
            if compute is None:
                return out, res
            return out, res, compute()
        if self.spec.schedule == "topk":
            # compress x/n so the residual tracks the mean-scale error
            scaled = jax.tree.map(lambda x: x / self.n_shards, tree)
            return self._reduce_split(scaled, res, t, compute)
        ret = self._reduce_split(tree, res, t, compute)
        out, res = ret[0], ret[1]
        out = jax.tree.map(lambda x: x / self.n_shards, out)
        return (out, res) if compute is None else (out, res, ret[2])

    def _reduce_split(self, tree, res, t, compute=None):
        """Dense-psum the ineligible leaves, run the schedule on the
        eligible ones."""
        import jax
        from jax import lax

        leaves, treedef = jax.tree.flatten(tree)
        comp = [x for x, e in zip(leaves, self._eligible_mask) if e]
        if len(self._eligible_mask) != len(leaves):
            raise ValueError(
                f"CommSync built for {len(self._eligible_mask)} leaves,"
                f" got {len(leaves)}")
        comp_out, res_new, aux = self._run_schedule(comp, res, t,
                                                    compute)
        it = iter(comp_out)
        out = [next(it) if e else lax.psum(x, self.axis_name)
               for x, e in zip(leaves, self._eligible_mask)]
        out = jax.tree.unflatten(treedef, out)
        return (out, res_new) if compute is None \
            else (out, res_new, aux)

    def _run_schedule(self, comp, res, t, compute=None):
        import jax
        import jax.numpy as jnp
        from jax import lax

        sched = self.spec.schedule
        shapes = [x.shape for x in comp]
        dtypes = [x.dtype for x in comp]
        sizes = [int(np.prod(s)) for s in shapes]

        def flatten(xs):
            return jnp.concatenate(
                [x.astype(jnp.float32).ravel() for x in xs]) \
                if xs else jnp.zeros((0,), jnp.float32)

        def unflatten(v):
            out, off = [], 0
            for shape, dt, sz in zip(shapes, dtypes, sizes):
                out.append(v[off:off + sz].reshape(shape).astype(dt))
                off += sz
            return out

        aux = None

        if sched == "bf16":
            aux = compute() if compute is not None else None
            out = [lax.psum(x.astype(jnp.bfloat16), self.axis_name)
                   .astype(x.dtype) for x in comp]
            return out, res, aux

        if sched == "topk":
            n = self.n_shards
            flat = flatten(comp) + res[0]
            k = max(1, int(round(self.spec.topk_fraction
                                 * max(1, self.ef_elems))))
            _, idx = lax.top_k(jnp.abs(flat), k)
            vals = flat[idx]
            # independent caller math next to the pair exchange — the
            # sparse all-gather is the schedule's one in-flight bucket
            aux = compute() if compute is not None else None
            out = sparse_allreduce(vals, idx, flat.shape[0],
                                   axis_name=self.axis_name, n=n)
            contrib = jnp.zeros_like(flat).at[idx].set(vals)
            return unflatten(out), (flat - contrib)[None, :], aux

        if sched in ("bucketed", "hier", "int8"):
            n = self.n_shards
            g = self.groups if sched == "hier" else 1
            m = max(1, n // g)
            # ring chunking granularity: n blocks for the flat ring,
            # n/g intra-group blocks for the two-level ring. g == n or
            # g == 1 degenerate to the flat ring (m == 1 has no
            # intra-group phase), whose padding granularity is n.
            n_blocks = m if (sched == "hier" and m > 1 and g > 1) \
                else n
            flat = flatten(comp)
            e = flat.shape[0]
            if sched in ("bucketed", "int8"):
                n_buckets = max(1, math.ceil(e / self.spec.bucket_elems))
            else:
                n_buckets = 1
            bucket = n_blocks * math.ceil(
                max(1, e) / (n_buckets * n_blocks))
            pad = n_buckets * bucket - e
            flat = jnp.pad(flat, (0, pad))
            buckets = flat.reshape(n_buckets, bucket)

            if sched == "int8":
                exchange, finish = self._int8_bucket_ring(bucket, t)
            else:
                ring = (_ring_allreduce if sched == "bucketed"
                        else lambda v, a, nn: _hier_allreduce(
                            v, a, nn, g))

                def exchange(b, i):
                    del i
                    return ring(b, self.axis_name, n)

                def finish(b):
                    return b

            # double-buffered bucket pipeline: bucket b's collective
            # chain overlaps bucket b−1's unpack/dequantize (and the
            # caller's `compute` thunk rides next to the first bucket)
            out, aux = _pipelined_buckets(
                buckets, exchange, finish, self.spec.overlap, compute)
            return unflatten(out.reshape(-1)[:e]), res, aux

        raise AssertionError(f"unreachable schedule {sched!r}")

    def _int8_bucket_ring(self, bucket: int, t):
        """The native int8 ring's per-bucket (exchange, finish) pair.

        ``exchange``: quantize the f32 bucket against a pmax-shared
        scale (seeded stochastic rounding), ``all_to_all`` the int8
        chunks so chunk *c* of every shard lands on shard *c* (int8 on
        the wire), accumulate the n integer contributions EXACTLY in
        int32 (order-free ⇒ bitwise-deterministic and replicated by
        construction), requantize the reduced chunk with a second
        seeded stochastic rounding (scale ``n·s`` bounds the integer
        sum, |Σq| ≤ 127n), then ring all-gather the int8 result chunk
        with origin placement. ``finish``: dequantize — the only f32
        work, pipelined against the NEXT bucket's exchange. The int32
        widening happens strictly AFTER the collectives (TDA051 polices
        the opposite order — the int32-psum wire this replaced)."""
        import jax
        import jax.numpy as jnp
        from jax import lax

        n = self.n_shards
        chunk = bucket // n
        axis = self.axis_name
        my = lax.axis_index(axis)
        key = jax.random.fold_in(
            jax.random.fold_in(jax.random.key(self.spec.seed), t), my)

        def exchange(b, i):
            scale = lax.pmax(jnp.max(jnp.abs(b)), axis) / 127.0
            scale = jnp.maximum(scale, jnp.float32(1e-30))
            u = jax.random.uniform(
                jax.random.fold_in(key, 2 * i), b.shape)
            q = jnp.clip(jnp.floor(b / scale + u),
                         -127, 127).astype(jnp.int8)
            # chunk c of every shard → shard c, as int8
            recv = lax.all_to_all(
                q.reshape(n, chunk), axis,
                split_axis=0, concat_axis=0, tiled=True)
            s_int = jnp.sum(recv.astype(jnp.int32), axis=0)  # exact
            u2 = jax.random.uniform(
                jax.random.fold_in(key, 2 * i + 1), s_int.shape)
            q2 = jnp.clip(jnp.floor(s_int.astype(jnp.float32) / n + u2),
                          -127, 127).astype(jnp.int8)
            return _ring_allgather(q2, axis, n), scale

        def finish(carry):
            all_q2, scale = carry
            # chunk c sits in row c: row-major reshape restores order
            return (all_q2.astype(jnp.float32)
                    * (scale * n)).reshape(-1)

        return exchange, finish

    # ---------------------------------------------------------- stats

    def stats(self) -> dict:
        """Per-sync byte accounting (host-side, static): per-shard
        ``bytes_wire`` under a bandwidth-optimal ring at the schedule's
        wire precision, the f32 ``bytes_logical`` payload, and the
        collective ``rounds`` launched per sync.

        This is the SCHEDULE'S payload accounting — what each sync
        fundamentally has to move — and since round 11 every schedule
        matches it on the wire: a bf16 psum moves bf16, topk's ring
        all-gather moves exactly the 8k-byte pair buffers, and int8
        runs the NATIVE compressed ring (``all_to_all`` chunk scatter +
        int8 ring all-gather, 1 byte/elem in both phases — the int32
        widening happens locally after the exchange, never on the
        wire; PR 5's int32-psum caveat is closed, and lint rule TDA051
        keeps it closed)."""
        dense_elems = sum(
            s for s, e in zip(self._sizes, self._eligible_mask)
            if not e)
        return schedule_stats(
            self.spec.schedule, n_shards=self.n_shards,
            compressible_elems=self.ef_elems, dense_elems=dense_elems,
            bucket_elems=self.spec.bucket_elems,
            topk_fraction=self.spec.topk_fraction, groups=self.groups)


def schedule_stats(schedule: str, *, n_shards: int,
                   compressible_elems: int, dense_elems: int = 0,
                   bucket_elems: int = 1 << 16,
                   topk_fraction: float = 0.01,
                   groups: int = 1) -> dict:
    """The closed-form per-sync byte/round accounting of one schedule
    — ``CommSync.stats`` minus the live sync object, callable from a
    plain parameter set (numpy-free, jax-free).

    This module-level spelling exists for the autotuner: the
    ``tune/resolve.py`` cost model joins these counts against a
    measured :mod:`tpu_distalg.tune.profile` (wire bandwidth, RTT,
    codec throughput) to predict per-sync seconds per candidate
    schedule, so the resolver and the live accounting can never
    disagree about what a schedule moves."""
    n = n_shards
    ce = compressible_elems
    ring = 2.0 * (n - 1) / n if n > 1 else 0.0
    b_logical = 4 * (ce + dense_elems)
    dense_wire = 4 * dense_elems * ring
    if schedule == "dense" or n == 1:
        wire = 4 * ce * ring + dense_wire
        rounds = 1
    elif schedule == "bf16":
        wire = 2 * ce * ring + dense_wire
        rounds = 1 + (1 if dense_elems else 0)
    elif schedule == "int8":
        # native ring: int8 both phases (scatter (n−1)/n + gather
        # (n−1)/n = the ring constant at 1 byte/elem), one f32
        # pmax per BUCKET for the shared scale (the requant scale
        # n·s is derived, no extra collective)
        nb = max(1, math.ceil(max(1, ce) / bucket_elems))
        wire = ce * ring + 4 * nb * ring + dense_wire
        rounds = 3 * nb + (1 if dense_elems else 0)
    elif schedule == "topk":
        k = max(1, int(round(topk_fraction * max(1, ce))))
        # k (value, index) pairs exchanged all-gather-style
        wire = 8 * k * (n - 1) + dense_wire
        rounds = 1 + (1 if dense_elems else 0)
    elif schedule == "bucketed":
        wire = 4 * ce * ring + dense_wire
        rounds = max(1, math.ceil(max(1, ce) / bucket_elems)) \
            + (1 if dense_elems else 0)
    elif schedule == "hier":
        g = max(1, groups)
        m = max(1, n // g)
        ici = 4 * ce * (2.0 * (m - 1) / m if m > 1 else 0.0)
        dcn = 4 * (ce / m) * (2.0 * (g - 1) / g if g > 1 else 0.0)
        wire = ici + dcn + dense_wire
        rounds = 3 + (1 if dense_elems else 0)
    else:  # pragma: no cover
        raise AssertionError(schedule)
    return {"bytes_wire": int(round(wire)),
            "bytes_logical": int(round(b_logical)),
            "rounds": int(rounds)}


def make_sync(spec, mesh, example, *, axis_name: str = DATA_AXIS):
    """Build a :class:`CommSync` — ``spec`` may be a :class:`CommSpec`
    or its CLI string spelling."""
    return CommSync(CommSpec.parse(spec), mesh, example,
                    axis_name=axis_name)


def emit_sync_counters(sync: CommSync, n_syncs: int) -> dict:
    """Bump the ``comm.*`` telemetry counters for a run of ``n_syncs``
    sync rounds (a no-op when telemetry is disabled) and return the
    per-sync stats for callers that also report them inline."""
    from tpu_distalg.telemetry import events as tevents

    st = sync.stats()
    tevents.counter("comm.bytes_wire", st["bytes_wire"] * n_syncs)
    tevents.counter("comm.bytes_logical",
                    st["bytes_logical"] * n_syncs)
    tevents.counter("comm.rounds", st["rounds"] * n_syncs)
    tevents.counter("comm.syncs", n_syncs)
    return st


def rank_combine_stats(k: int, length: int, n: int) -> dict:
    """Byte accounting for a window-sparse vector combine — the graph
    engine's rank-contribution reduce (``graphs/engine.py``), where
    every shard contributes ``k`` (value, index) pairs covering the
    destination window its edge blocks touch.

    ``bytes_wire`` is the sparse exchange's per-shard payload: the ring
    all-gather of the pair buffers (:func:`sparse_allreduce`) moves
    ``8k`` bytes per hop over ``n−1`` hops — on power-law graphs ``k``
    (the shard's distinct-destination count) is a small fraction of the
    vertex count, the observation Sparse Allreduce (arXiv:1312.3020)
    is built on. ``bytes_dense_ring`` is what the dense alternative — a
    psum of the O(length) zero-padded vector under a bandwidth-optimal
    ring — would move: ``4·length·2(n−1)/n``. ``bytes_logical`` is the
    f32 payload logically reduced (the dense length), so the standard
    ``comm.bytes_wire``/``bytes_logical`` counters render the achieved
    compression in ``tda report`` exactly like the gradient schedules'.
    """
    ring = 2.0 * (n - 1) / n if n > 1 else 0.0
    return {
        "bytes_wire": int(8 * k * max(0, n - 1)),
        "bytes_dense_ring": int(round(4 * length * ring)),
        "bytes_logical": int(4 * length),
        "rounds": 1,
    }


def emit_rank_combine_counters(k: int, length: int, n: int, *,
                               n_syncs: int = 1,
                               combine: str = "sparse") -> dict:
    """Bump the telemetry counters for ``n_syncs`` rank combines and
    return the per-sync accounting. ``comm.bytes_wire`` carries the
    payload of the combine actually run (``combine='dense'`` runs the
    psum, so its wire bytes are the dense-ring figure); the
    ``graph.combine_*`` pair records BOTH accountings so the report can
    state the sparse-vs-dense win for the run whichever was selected.
    No-op when telemetry is disabled."""
    from tpu_distalg.telemetry import events as tevents

    st = rank_combine_stats(k, length, n)
    wire = (st["bytes_wire"] if combine == "sparse"
            else st["bytes_dense_ring"])
    tevents.counter("comm.bytes_wire", wire * n_syncs)
    tevents.counter("comm.bytes_logical", st["bytes_logical"] * n_syncs)
    tevents.counter("comm.rounds", st["rounds"] * n_syncs)
    tevents.counter("comm.syncs", n_syncs)
    tevents.counter("graph.combine_bytes_wire", wire * n_syncs)
    tevents.counter("graph.combine_bytes_dense_ring",
                    st["bytes_dense_ring"] * n_syncs)
    tevents.counter("graph.combine_syncs", n_syncs)
    return st


# --------------------------------------------------------------------
# Host-side wire codecs — the cluster tier's spelling of the schedules.
#
# The device schedules above compress SPMD collectives; the
# multi-process cluster (tpu_distalg/cluster/) moves the same payloads
# over a real TCP wire, host-to-host, where the quantize/dequantize +
# error-feedback stages run in numpy BEFORE transport framing. These
# codecs are that reusable stage: pure functions of (spec.seed, the
# caller's integer path) — the host-side counterpart of the device
# threefry fold-in chain — so every process reconstructs identical
# bytes and a chaos replay stays bitwise. numpy + stdlib only: the
# coordinator process never imports jax.
#
#   int8  seeded stochastic rounding against a shared max-abs scale;
#         the decoder widens int8 -> int32 EXACTLY before the single
#         f32 scale multiply (the wire itself carries 1 byte/elem —
#         TDA051 polices the opposite order).
#   topk  the k largest-|.| entries as (value, index) pairs — 8k pair
#         bytes on the wire; the decoder scatter-adds them exactly.
#
# Both run under ERROR FEEDBACK when the caller carries a residual:
# ``encode(vec)`` compresses ``vec + residual`` and returns the new
# residual (what the wire did not carry), so nothing is ever lost —
# the EF-SGD correction of the device topk schedule, applied uniformly
# (stochastic int8 is unbiased already; EF additionally bounds its
# worst case). The residual is the caller's to checkpoint/resume.


#: seed-path direction tags — a cluster push folds in
#: ``(PUSH_SEED_TAG, slot, window)``, a pull ``(PULL_SEED_TAG, slot,
#: have, version)``: the two directions can never share a rounding
#: stream
PUSH_SEED_TAG = 1
PULL_SEED_TAG = 2


def host_rng(seed: int, *path: int) -> np.random.Generator:
    """Counter-based generator keyed by ``(seed, path...)`` — the
    host-side stand-in for ``jax.random.fold_in`` chains (Philox under
    a SeedSequence; both are spec-fixed, so the stream is stable
    across platforms and numpy versions)."""
    ss = np.random.SeedSequence(
        entropy=int(seed) & 0xFFFFFFFFFFFFFFFF,
        spawn_key=tuple(int(p) & 0xFFFFFFFF for p in path))
    return np.random.Generator(np.random.Philox(ss))


class HostCodec:
    """Base: a stateless vector codec; EF residual rides the caller."""

    #: frames-on-the-wire name (welcome meta / telemetry)
    name = "?"

    def __init__(self, spec: "CommSpec"):
        self.spec = spec

    def encode(self, vec: np.ndarray, residual: np.ndarray | None,
               *path: int):
        """``(arrays, residual_new)`` for one f32 vector. ``path`` is
        the deterministic seed path — (direction, slot, window) for a
        worker push, (direction, slot, have, version) for a pull."""
        raise NotImplementedError

    def decode(self, arrays: dict, length: int) -> np.ndarray:
        """The dense f32 ``(length,)`` reconstruction — exact integer
        widening / scatter-add, deterministic on every host."""
        raise NotImplementedError


class Int8HostCodec(HostCodec):
    """Seeded stochastic rounding to int8 against a max-abs scale."""

    name = "int8"

    def encode(self, vec, residual, *path):
        x = np.asarray(vec, np.float32)
        if residual is not None:
            x = x + residual
        scale = np.float32(max(float(np.max(np.abs(x)))
                               if x.size else 0.0, 1e-30) / 127.0)
        u = host_rng(self.spec.seed, *path).random(
            x.shape, dtype=np.float32)
        q = np.clip(np.floor(x / scale + u), -127, 127).astype(np.int8)
        # shape (1,): the transport frames scalars at min-ndim 1
        arrays = {"q": q, "scale": np.full((1,), scale, np.float32)}
        res_new = (x - q.astype(np.float32) * scale
                   if residual is not None else None)
        return arrays, res_new

    def decode(self, arrays, length):
        q = np.asarray(arrays["q"])
        # EXACT widening strictly after the wire (TDA051's contract),
        # then the one f32 scale multiply
        wide = q.astype(np.int32)
        return (wide.astype(np.float32)
                * np.float32(arrays["scale"])).reshape(length)


class TopkHostCodec(HostCodec):
    """The k largest-|.| entries as (value, index) pairs."""

    name = "topk"

    def k_for(self, length: int) -> int:
        return max(1, int(round(self.spec.topk_fraction
                                * max(1, length))))

    def encode(self, vec, residual, *path):
        x = np.asarray(vec, np.float32)
        if residual is not None:
            x = x + residual
        k = self.k_for(x.size)
        # stable sort => deterministic tie-breaks on every host
        idx = np.argsort(-np.abs(x), kind="stable")[:k].astype(np.int32)
        vals = x[idx]
        arrays = {"vals": vals, "idx": idx}
        if residual is None:
            return arrays, None
        res_new = x.copy()
        res_new[idx] = 0.0
        return arrays, res_new

    def decode(self, arrays, length):
        out = np.zeros((length,), np.float32)
        # exact scatter-add (duplicate indices accumulate additively)
        np.add.at(out, np.asarray(arrays["idx"], np.int64),
                  np.asarray(arrays["vals"], np.float32))
        return out


#: schedules the cluster wire admits (the device-only schedules —
#: bucketed/hier/bf16 — have no host spelling worth framing: bf16
#: halves bytes where int8 quarters them, bucketing is a collective-
#: overlap concern, and hier is a topology concern)
HOST_SCHEDULES = ("dense", "int8", "topk")


def make_host_codec(spec) -> HostCodec | None:
    """The host codec for a :class:`CommSpec` (or its CLI string) —
    ``None`` for ``dense`` (callers keep their uncompressed path
    verbatim, which is what pins dense bitwise to history)."""
    spec = CommSpec.parse(spec)
    if spec.schedule not in HOST_SCHEDULES:
        raise ValueError(
            f"comm schedule {spec.schedule!r} has no host-wire "
            f"codec; the cluster tier takes one of "
            f"{', '.join(HOST_SCHEDULES)}")
    if spec.schedule == "int8":
        return Int8HostCodec(spec)
    if spec.schedule == "topk":
        return TopkHostCodec(spec)
    return None


def make_host_pull_codec(spec) -> HostCodec | None:
    """The PULL-direction codec: int8 under EVERY compressed mode
    (``None`` for dense). The push direction can afford topk's biased
    truncation because the worker-side EF residual re-sends dropped
    mass later; the pull direction has no residual channel — pair
    pulls would silently lose the untransmitted (1−frac) of every
    center delta from the worker's cached view forever, or require
    durable per-worker residual state at the coordinator that every
    ack would have to WAL before leaving. int8's stochastic rounding
    is unbiased and stateless, so a recovered coordinator re-serves
    bit-identical pulls from the replayed center history alone. Both
    ends derive this codec from the same spec, so they can never
    disagree on the wire format."""
    spec = CommSpec.parse(spec)
    return (None if make_host_codec(spec) is None
            else Int8HostCodec(spec))


def encode_tree(codec: HostCodec, tree: dict,
                residuals: dict | None, *path: int):
    """Per-leaf host encode of a flat ``{name: ndarray}`` tree (the
    cluster center/delta vocabulary): each float leaf flattens, rides
    the codec under seed path ``(*path, leaf_index)``, and lands as
    ``{name}.{part}`` wire arrays. Returns ``(arrays,
    residuals_new)``; ``residuals`` maps name -> flat f32 residual
    (or ``None`` for EF-free encoding)."""
    arrays: dict = {}
    res_new: dict | None = None if residuals is None else {}
    for i, name in enumerate(sorted(tree)):
        leaf = np.asarray(tree[name], np.float32).ravel()
        res = None if residuals is None else residuals.get(
            name, np.zeros_like(leaf))
        parts, r = codec.encode(leaf, res, *path, i)
        for part, arr in parts.items():
            arrays[f"{name}.{part}"] = arr
        if res_new is not None:
            res_new[name] = r
    return arrays, res_new


def decode_tree(codec: HostCodec, arrays: dict,
                template: dict) -> dict:
    """Inverse of :func:`encode_tree` under a shape template
    ``{name: ndarray-like}`` (the model's known center layout)."""
    out = {}
    for name in sorted(template):
        shape = np.asarray(template[name]).shape
        length = int(np.prod(shape, dtype=np.int64)) if shape else 1
        prefix = f"{name}."
        parts = {k[len(prefix):]: v for k, v in arrays.items()
                 if k.startswith(prefix)}
        out[name] = codec.decode(parts, length).reshape(shape)
    return out


def merge_topk_pairs_host(all_vals, all_idx, *, k: int):
    """Host spelling of ``ops.pallas_topk.merge_topk_pairs`` — the
    cross-PROCESS half of the sparse candidate merge. A router holding
    per-replica (S, B, K) pair stacks gathered over the framed
    transport merges them with the same two-key order the in-process
    ring all-gather path uses: value DESCENDING, ties toward the LOWER
    global index (``lax.top_k``'s rule). Scores are computed and
    compared as the same f32 bits on both paths, so routed sharded
    replies stay bitwise-identical to a single-replica run."""
    v = np.moveaxis(np.asarray(all_vals, np.float32), 0, 1)
    i = np.moveaxis(np.asarray(all_idx, np.int32), 0, 1)
    B = v.shape[0]
    v = v.reshape(B, -1)
    i = i.reshape(B, -1)
    out_v = np.empty((B, k), np.float32)
    out_i = np.empty((B, k), np.int32)
    for b in range(B):
        # lexsort: LAST key is primary — (-value asc, index asc)
        order = np.lexsort((i[b], -v[b]))[:k]
        out_v[b] = v[b][order]
        out_i[b] = i[b][order]
    return out_v, out_i


def zero_residuals(template: dict) -> dict:
    """Fresh EF residuals for a tree template — one flat f32 zero
    vector per leaf (what a brand-new or reset worker carries)."""
    return {name: np.zeros(
        int(np.prod(np.asarray(template[name]).shape,
                    dtype=np.int64)), np.float32)
        for name in template}


def emit_overlap_counters(hidden_ms: float, comm_ms: float) -> None:
    """Bump the overlap-efficiency counters ``tda report`` renders:
    ``comm.overlap_hidden_ms`` is comm time HIDDEN behind compute
    (measured as the sequential-vs-overlapped step-time delta × sync
    count — the honest host-side observable), ``comm.sync_ms`` the comm
    time still exposed (schedule-vs-dense delta under overlap). The
    report line shows hidden / (hidden + exposed) as the fraction of
    comm time the pipeline hid. No-op when telemetry is disabled."""
    from tpu_distalg.telemetry import events as tevents

    tevents.counter("comm.overlap_hidden_ms",
                    max(0, int(round(hidden_ms))))
    tevents.counter("comm.sync_ms", max(0, int(round(comm_ms))))
