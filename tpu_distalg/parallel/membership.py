"""Elastic shard membership — generation-numbered epochs.

The second half of the stale-synchronous layer (``parallel/ssp.py``):
the PARTICIPANT SET may change while training runs. A shard leaves at a
window boundary (in production: the ``Preempted`` rc-75 exit of PR 3's
machinery — the subprocess test drives exactly that path) and rejoins
later; the comms layer renegotiates at each membership change — a new
GENERATION gets a freshly derived ring/bucket geometry (the merge
``CommSync`` and clock combine are rebuilt for the epoch's active set)
and the sharded optimizer state is redistributed at the boundary. The
portable-redistribution blueprint (arXiv:2112.01075) is followed where
it is cheap and honest for this state family: every epoch boundary
coincides with a merge, where per-replica models resync from the
replicated center and error-feedback residuals have just been flushed
into the contribution — so redistribution is re-DERIVATION from the
replicated state at the new geometry, never a resharding of torn
per-device buffers.

Two complementary mechanisms, both deterministic:

  * IN-PROCESS epochs: ``compile_epochs`` turns the seeded fault
    plan's ``shard:leave`` rules into a generation-numbered epoch list
    (one ``faults.probe`` per (boundary, shard) cell, fixed order — a
    pure function of the plan, replayed bitwise). Departed shards'
    devices keep executing the SPMD program (a collective cannot run
    without them) but are masked: zero merge weight, no local steps —
    on an emulated single-host mesh that is the honest statement of
    what "left" means.
  * CROSS-PROCESS elasticity: a checkpointed SSP run resumed with a
    DIFFERENT ``--n-slices`` renegotiates instead of rejecting — the
    persisted state is shard-count-agnostic (replicated center + step
    clocks), the generation bumps, per-shard state is re-derived at
    the new geometry, and the run completes. Leaving = the rc-75
    preemption exit; rejoining = re-running with the shard back.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from tpu_distalg.faults import registry as fregistry


@dataclasses.dataclass(frozen=True)
class Epoch:
    """One membership generation: windows [start, end) run with the
    fixed ``active`` shard set."""

    gen: int
    start: int                 # first window index (inclusive)
    end: int                   # last window index (exclusive)
    active: tuple[bool, ...]   # per logical shard

    @property
    def n_active(self) -> int:
        return sum(self.active)


def compile_epochs(n_windows: int, n_shards: int, *,
                   plan=None) -> list[Epoch]:
    """Membership epochs from the fault plan's ``shard:leave`` rules:
    one probe per (window boundary, shard) in row-major order against
    a FRESH registry built from the plan (a pure function of the plan,
    like the straggle schedule — restarts and resumes recompile the
    identical epochs; fires are mirrored into the live ledger); a
    fired ``leave:r`` rule marks the shard absent for the next
    ``ceil(r)`` windows (default ``DEFAULT_LEAVE_WINDOWS``), rejoining
    after. Overlapping absences extend. A leave that would empty the
    active set is ignored — the mesh never goes quorumless — and the
    generation number increments at every membership CHANGE, so epoch
    boundaries are exactly the ring renegotiations."""
    live = fregistry.active()
    if plan is None:
        plan = live.plan if live is not None else None
    absent_until = np.zeros(n_shards, np.int64)
    has_rules = plan is not None and any(
        r.point == "shard:leave" for r in plan.rules)
    # quiet: fires reach telemetry once, via live.record() at the end
    reg = (fregistry.FaultRegistry(plan, quiet=True)
           if has_rules else None)
    epochs: list[Epoch] = []
    gen = 1
    cur: tuple[bool, ...] | None = None
    for b in range(n_windows):
        if has_rules:
            for k in range(n_shards):
                hit = reg.probe("shard:leave")
                if hit is None:
                    continue
                _, arg = hit
                away = int(np.ceil(arg if arg is not None
                                   else fregistry.DEFAULT_LEAVE_WINDOWS))
                absent_until[k] = max(absent_until[k], b + max(1, away))
        active = tuple(bool(absent_until[k] <= b)
                       for k in range(n_shards))
        if not any(active):
            # never quorumless: the longest-absent shard is retained
            keep = int(np.argmin(absent_until))
            active = tuple(k == keep for k in range(n_shards))
        if active != cur:
            if epochs:
                epochs[-1] = dataclasses.replace(epochs[-1], end=b)
            if cur is not None:
                gen += 1
            epochs.append(Epoch(gen=gen, start=b, end=n_windows,
                                active=active))
            cur = active
    if not epochs:
        epochs.append(Epoch(gen=1, start=0, end=n_windows,
                            active=(True,) * n_shards))
    if reg is not None and live is not None and live.plan == plan:
        live.record(reg.fired)
    return epochs


def emit_epoch_event(epoch: Epoch, *, reason: str,
                     prev_active: int | None = None) -> None:
    """Record a ring renegotiation: a ``membership_epoch`` event plus
    the ``ssp.membership_epochs`` counter feed ``tda report``'s SSP
    line. No-op when telemetry is disabled."""
    from tpu_distalg.telemetry import events as tevents

    tevents.emit("membership_epoch", gen=epoch.gen,
                 n_active=epoch.n_active,
                 prev_active=prev_active, reason=reason,
                 active=[int(a) for a in epoch.active])


def redistribute_clocks(clocks, n_new: int):
    """Clock vector for a renegotiated geometry: a cross-process
    membership change is a FULL resync boundary (the checkpointed
    center is the state everyone restarts from), so every member of
    the new generation resumes at the maximum clock — ages start at
    zero against the freshest model, which is exactly what a rejoining
    shard holds after redistribution."""
    c = np.asarray(clocks)
    top = int(c.max()) if c.size else 0
    return np.full((n_new,), top, np.int64)


def describe_renegotiation(gen: int, n_old: int, n_new: int) -> str:
    return (f"[ssp] ring renegotiated: {n_old} -> {n_new} shard(s), "
            f"membership generation {gen} (geometry re-derived; "
            f"sharded state re-derived from the replicated center)")


def run_elastic(
    checkpoint_dir: str | None,
    checkpoint_every: int,
    n_windows: int,
    n_shards: int,
    *,
    make_seg_fn,
    run_seg,
    state0,
    renegotiate=None,
    on_epoch=None,
    tag: str = "",
    ticks_per_window: int = 1,
    keep: int = 3,
    logger=None,
):
    """The elastic windowed training loop — ``run_segmented``'s shape
    at WINDOW granularity with membership epochs layered in.

    Epochs come from :func:`compile_epochs` (the active plan's seeded
    ``shard:leave`` rules); each segment runs with ONE fixed active set
    and one compiled fn (``make_seg_fn(active, n_win)``, cached), and
    segment boundaries are the union of epoch boundaries and
    ``checkpoint_every``-window checkpoints. ``run_seg(fn, state, win0,
    n_win, epoch)`` executes a segment and returns ``(state, outs)``
    where ``outs`` is a tuple of per-window host arrays (accuracy and
    staleness traces), concatenated across segments by the driver.

    In-process membership changes need NO state surgery: the SSP
    program re-derives a rejoining shard's local state from the
    replicated center at its adopt step (and a departing shard's
    pending delta is parked exactly like a preempted worker's would
    be); the driver's job at an epoch boundary is the renegotiation
    record and the fresh compiled geometry.

    Cross-process elasticity rides the checkpoint: the payload records
    the writing geometry's shard count, and a resume on a DIFFERENT
    shard count calls ``renegotiate(saved_leaves, saved_shards,
    start_window)`` — the trainer re-derives per-shard state from the
    replicated center — instead of rejecting. Preemption exits at the
    next segment boundary AFTER the durable save with the distinct
    rc 75 (never burning restart budget), which is precisely the
    "leave at a ``Preempted`` boundary" contract: the departed
    process's shards rejoin when the command re-runs.

    Returns ``(state, outs_concat, start_window, epochs)``.
    """
    import jax

    from tpu_distalg import faults
    from tpu_distalg.telemetry import events as tevents
    from tpu_distalg.utils import checkpoint as ckpt
    from tpu_distalg.utils import metrics

    if checkpoint_every < 1:
        raise ValueError(
            f"checkpoint_every must be >= 1, got {checkpoint_every}")
    if logger is None:
        import functools
        import sys

        logger = functools.partial(print, file=sys.stderr)
    log = logger
    epochs = compile_epochs(n_windows, n_shards)
    leaves0, treedef = jax.tree.flatten(state0)
    state = state0
    start = 0
    outs_parts: list[tuple[np.ndarray, ...]] = []

    if checkpoint_dir:
        restored = ckpt.restore_newest_with_fallback(checkpoint_dir,
                                                     logger=logger)
    else:
        restored = None
    if restored is not None:
        payload, start = restored
        saved_tag = ckpt.decode_tag(payload, tag)
        # the tag check comes FIRST: a foreign checkpoint's step count
        # is in that workload's units (ticks vs windows), so any other
        # diagnosis about it would mislead
        if "state" not in payload or saved_tag != tag:
            raise ValueError(
                f"checkpoint in {checkpoint_dir} holds workload "
                f"{saved_tag!r}, this run is {tag!r} — written by a "
                f"different workload or framework version; use a "
                f"fresh directory")
        if start > n_windows:
            raise ValueError(
                f"checkpoint in {checkpoint_dir} is at window {start}, "
                f"past n_windows={n_windows}; use a fresh directory")
        saved_shards = int(np.asarray(payload.get("shards", n_shards)))
        saved_leaves = [np.asarray(v) for v in payload["state"]]
        if saved_shards != n_shards:
            if renegotiate is None:
                raise ValueError(
                    f"checkpoint in {checkpoint_dir} was written at "
                    f"{saved_shards} shard(s), this mesh has "
                    f"{n_shards} and the workload does not support "
                    f"elastic renegotiation")
            cur = next((e for e in epochs if e.start <= start < e.end),
                       epochs[-1])
            state = renegotiate(saved_leaves, saved_shards, start)
            emit_epoch_event(cur, reason="renegotiated_resume",
                             prev_active=saved_shards)
            tevents.counter("ssp.membership_epochs")
            log(describe_renegotiation(cur.gen, saved_shards, n_shards))
        else:
            sig = [(tuple(v.shape), str(v.dtype)) for v in saved_leaves]
            want = [(tuple(np.asarray(x).shape),
                     str(np.asarray(x).dtype)) for x in leaves0]
            if sig != want:
                raise ValueError(
                    f"checkpoint in {checkpoint_dir} state {sig} does "
                    f"not match this run's {want} — different config "
                    f"or framework version; use a fresh directory")
            state = jax.tree.unflatten(treedef, saved_leaves)
        outs_parts = [tuple(np.asarray(v)
                            for v in payload.get("outs", []))]

    seg_fns: dict = {}
    win = start
    # seed prev_epoch from the window BEFORE the resume point: a
    # preempt exit lands exactly on segment boundaries, which include
    # every epoch boundary — without this, a resume landing on a
    # membership transition would skip the on_epoch fixup (the EASGD
    # rejoiner clock bump) and recreate the frozen-clock gate stall
    prev_epoch: Epoch | None = None
    if start > 0:
        prev_epoch = next((e for e in epochs
                           if e.start <= start - 1 < e.end), None)
    while win < n_windows:
        epoch = next(e for e in epochs if e.start <= win < e.end)
        if prev_epoch is not None and epoch.gen != prev_epoch.gen:
            emit_epoch_event(epoch, reason="membership_change",
                             prev_active=prev_epoch.n_active)
            tevents.counter("ssp.membership_epochs")
            log(f"[ssp] membership epoch {epoch.gen}: "
                f"{epoch.n_active}/{n_shards} shard(s) active")
            if on_epoch is not None:
                # trainer hook for membership-transition state fixups
                # the compiled program cannot express (e.g. EASGD never
                # resyncs, so a rejoiner's frozen clock must be bumped
                # HERE or the gate would serialize the mesh onto it)
                state = on_epoch(state, prev_epoch, epoch)
        prev_epoch = epoch
        seg_end = min(epoch.end,
                      ((win // checkpoint_every) + 1) * checkpoint_every,
                      n_windows)
        n_win = seg_end - win
        tevents.mark(f"ssp:{tag or 'train'}@w{win}", emit_event=False)
        faults.inject("segment:run")
        key = (epoch.active, n_win)
        if key not in seg_fns:
            seg_fns[key] = make_seg_fn(epoch.active, n_win)
        state, outs = run_seg(seg_fns[key], state, win, n_win, epoch)
        metrics.guard_finite(state, f"SSP state after window {seg_end}")
        outs_parts.append(tuple(np.asarray(o) for o in outs))
        win = seg_end
        if checkpoint_dir:
            streams = _cat_streams(outs_parts)
            ckpt.save(
                checkpoint_dir,
                {"tag": ckpt.encode_tag(tag),
                 "shards": np.int64(n_shards),
                 "state": [np.asarray(x)
                           for x in jax.tree.leaves(state)],
                 "outs": streams},
                step=win)
            ckpt.prune(checkpoint_dir, keep=keep)
            tevents.emit("checkpoint_saved",
                         step=win * ticks_per_window, tag=tag)
            tevents.counter("checkpoints_saved")
            if win < n_windows:
                # shared boundary-exit contract (no-op when no request
                # is pending) — the "leave at a Preempted boundary"
                # path itself
                ckpt.preempt_boundary_exit(win * ticks_per_window, tag)
    return state, _cat_streams(outs_parts), start, epochs


def _cat_streams(parts) -> list[np.ndarray]:
    """Concatenate per-segment output tuples stream-wise, skipping
    empty tuples (a resumed run whose checkpoint predates any
    segment's outputs)."""
    parts = [p for p in parts if p]
    if not parts:
        return []
    return [np.concatenate([p[i] for p in parts])
            for i in range(len(parts[0]))]
