"""Mesh/runtime core and the collectives/dataflow layer.

This package is the Spark replacement (SURVEY.md §2.2): everything the
reference scripts obtained from ``spark.sparkContext`` — RDD creation,
broadcast, tree aggregation, keyed reduction, per-partition compute — has a
TPU-native equivalent here, built on ``jax.sharding`` meshes, ``shard_map``
and XLA collectives.
"""

from tpu_distalg.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    MeshContext,
    get_mesh,
    local_device_count,
    multihost_initialize,
)
from tpu_distalg.parallel.sharding import (
    ShardedMatrix,
    build_sharded,
    data_sharding,
    pad_rows,
    parallelize,
    replicate,
    replicated_sharding,
)
from tpu_distalg.parallel.collectives import (
    all_gather,
    all_to_all,
    tree_allreduce_mean,
    tree_allreduce_sum,
    ring_shift,
)
from tpu_distalg.parallel.spmd import data_parallel, replica_index

__all__ = [
    "DATA_AXIS",
    "MODEL_AXIS",
    "MeshContext",
    "ShardedMatrix",
    "all_gather",
    "all_to_all",
    "build_sharded",
    "data_parallel",
    "data_sharding",
    "get_mesh",
    "local_device_count",
    "multihost_initialize",
    "pad_rows",
    "parallelize",
    "replica_index",
    "replicate",
    "replicated_sharding",
    "ring_shift",
    "tree_allreduce_mean",
    "tree_allreduce_sum",
]
