"""Mesh/runtime core and the collectives/dataflow layer.

This package is the Spark replacement (SURVEY.md §2.2): everything the
reference scripts obtained from ``spark.sparkContext`` — RDD creation,
broadcast, tree aggregation, keyed reduction, per-partition compute — has a
TPU-native equivalent here, built on ``jax.sharding`` meshes, ``shard_map``
and XLA collectives.
"""

from tpu_distalg.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    MeshContext,
    get_mesh,
    local_device_count,
    multihost_initialize,
)
from tpu_distalg.parallel.sharding import (
    ShardedMatrix,
    build_sharded,
    data_sharding,
    pad_rows,
    parallelize,
    replicate,
    replicated_sharding,
)
from tpu_distalg.parallel.collectives import (
    all_gather,
    all_to_all,
    tree_allreduce_mean,
    tree_allreduce_sum,
    ring_shift,
)
from tpu_distalg.parallel.comms import (
    CommSpec,
    CommSync,
    make_sync,
)
from tpu_distalg.parallel import membership, partition, ssp
from tpu_distalg.parallel.partition import RuleTable
from tpu_distalg.parallel.ssp import SyncSpec
from tpu_distalg.parallel.spmd import data_parallel, replica_index
from tpu_distalg.parallel.ring import (
    alltoall_head_to_seq,
    alltoall_seq_to_head,
    ring_allgather_matmul,
    ring_attention,
    softmax_attention,
    ulysses_attention,
    zigzag_inverse,
    zigzag_order,
)

__all__ = [
    "CommSpec",
    "CommSync",
    "DATA_AXIS",
    "MODEL_AXIS",
    "MeshContext",
    "RuleTable",
    "ShardedMatrix",
    "SyncSpec",
    "make_sync",
    "membership",
    "partition",
    "ssp",
    "all_gather",
    "all_to_all",
    "alltoall_head_to_seq",
    "alltoall_seq_to_head",
    "build_sharded",
    "data_parallel",
    "data_sharding",
    "get_mesh",
    "local_device_count",
    "multihost_initialize",
    "pad_rows",
    "parallelize",
    "replica_index",
    "replicate",
    "replicated_sharding",
    "ring_allgather_matmul",
    "ring_attention",
    "ring_shift",
    "softmax_attention",
    "tree_allreduce_mean",
    "tree_allreduce_sum",
    "ulysses_attention",
    "zigzag_inverse",
    "zigzag_order",
]
