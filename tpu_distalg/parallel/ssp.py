"""Stale-synchronous coordination — bounded staleness for the SGD family.

Every distributed-SGD trainer in this repo is bulk-synchronous (BSP):
one collective per step/round means one slow, preempted or rejoining
shard stalls the entire mesh, so wall-clock throughput is gated by the
WORST participant. This module is the bounded-staleness alternative the
ROADMAP's item 2 calls for: shards advance up to ``s`` ticks ahead of
the slowest peer, the cross-shard merge runs once per ``s``-tick
window instead of every tick, and a device-resident CLOCK VECTOR —
combined through the existing comms layer, so any ``--comm`` schedule
carries it — gates only the shards that exceed the bound. A straggler
no longer serializes every step: its delay overlaps the window's other
work, and its late contribution merges with STALENESS-WEIGHTED
averaging (weight ``decay^age``) instead of being waited for. The
MapReduce-over-a-clients-axis shape follows DrJAX (arXiv:2403.07128):
local-update work runs ``map``-style over the data axis with one
``reduce`` per window, which is exactly what lets the participant set
vary (``parallel/membership.py``).

Determinism contract (the property everything else in this repo rests
on): straggler and membership schedules are compiled HOST-SIDE from the
seeded fault plan (``shard:straggle`` / ``shard:leave`` rules,
``faults/registry.py``) by one :func:`faults.probe` call per
(tick, shard) cell in fixed row-major order — the schedule is a pure
function of the plan, the injected interference is deterministic
compute inside the program, and an SSP run replayed with the same plan
is bitwise-identical. ``--sync bsp`` does not touch this module's
program at all: the BSP trainers keep their pre-SSP XLA programs, so
the golden-hash pins hold by construction.

Why the speedup is real and not an accounting trick: under BSP the
per-tick collective is a barrier, so tick time is
``max_k(base + delay_k)`` and every shard's delay is paid serially by
the whole mesh. Under SSP the window's ``s`` ticks have NO cross-shard
data dependence — each device runs its own instruction stream until the
merge rendezvous — so delays on different shards overlap and the window
costs ``max_k Σ_t(base + delay_k(t))``. The bench's
``ssgd_ssp_straggler_speedup`` measures exactly that: full step time,
BSP vs SSP, under the same seeded straggler plan.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from tpu_distalg.faults import registry as fregistry

#: default staleness bound (ticks a shard may run ahead of the slowest)
DEFAULT_STALENESS = 4
#: default per-age decay of a late contribution's merge weight
DEFAULT_DECAY = 0.5
#: one straggle "unit" = one pass of the interference kernel over a
#: (STRAGGLE_LANES,) f32 vector — real FLOPs, deterministic values
STRAGGLE_LANES = 4096

SYNC_MODES = ("bsp", "ssp")


@dataclasses.dataclass(frozen=True)
class SyncSpec:
    """One run's synchronization discipline + knobs.

    ``parse`` accepts the CLI spelling: ``bsp`` (classic lock-step —
    the default, bitwise the pre-SSP trainers), ``ssp`` (bounded
    staleness at the default bound), ``ssp:8`` (bound 8 ticks),
    ``ssp:8:0.7`` (bound 8, staleness-weight decay 0.7).
    """

    mode: str = "bsp"
    staleness: int = DEFAULT_STALENESS  # ticks per merge window / bound
    decay: float = DEFAULT_DECAY        # weight = decay ** age

    def __post_init__(self):
        if self.mode not in SYNC_MODES:
            raise ValueError(
                f"unknown sync mode {self.mode!r}; want one of "
                f"{', '.join(SYNC_MODES)} (spellings: 'bsp', 'ssp', "
                f"'ssp:s', 'ssp:s:decay')")
        if self.staleness < 1:
            raise ValueError(
                f"ssp staleness bound must be >= 1, got {self.staleness}")
        if not (0.0 < self.decay <= 1.0):
            raise ValueError(
                f"ssp decay must be in (0, 1], got {self.decay}")

    @classmethod
    def parse(cls, text: str | "SyncSpec" | None) -> "SyncSpec":
        if isinstance(text, cls):
            return text
        if not text:
            return cls()
        parts = str(text).split(":")
        kw = {}
        if parts[0] != "ssp" and len(parts) > 1:
            # 'bsp:8' is almost certainly a typo of 'ssp:8' — silently
            # dropping the bound would train lock-step BSP against the
            # user's intent
            raise ValueError(
                f"bad --sync spelling {text!r}: only 'ssp' takes "
                f"arguments ('ssp:s', 'ssp:s:decay')")
        if len(parts) >= 2 and parts[1]:
            kw["staleness"] = int(parts[1])
        if len(parts) >= 3 and parts[2]:
            kw["decay"] = float(parts[2])
        if len(parts) > 3:
            raise ValueError(
                f"bad --sync spelling {text!r}: want 'bsp', 'ssp', "
                f"'ssp:s' or 'ssp:s:decay'")
        return cls(mode=parts[0], **kw)

    @property
    def is_ssp(self) -> bool:
        return self.mode == "ssp"

    def spec(self) -> str:
        if self.mode == "bsp":
            return "bsp"
        return f"ssp:{self.staleness}:{self.decay:g}"


def window_grid(n_ticks: int, staleness: int) -> tuple[int, int]:
    """(n_windows, padded_ticks): ticks are grouped into full
    ``staleness``-length windows; trailing pad ticks are masked no-ops
    (valid=False), so any ``n_iterations`` works."""
    n_win = max(1, -(-n_ticks // staleness))
    return n_win, n_win * staleness


def compile_straggle_schedule(n_ticks: int, n_shards: int, *,
                              plan=None) -> np.ndarray:
    """The (n_ticks, n_shards) int32 interference schedule, compiled
    from the fault plan's ``shard:straggle`` rules: cell (t, k) holds
    the straggle work units shard k pays at tick t (0 = none). One
    probe per cell in row-major order against a FRESH registry built
    from the plan — the schedule is a pure function of the plan (not
    of how many probes earlier compilations consumed), so a restarted
    or resumed run recompiles the identical schedule, which is what
    the bitwise-replay acceptance rests on. Fires are mirrored into
    the live registry's ledger so chaos verdicts and ``tda report``
    still see them. An empty/absent plan compiles an all-zero
    schedule."""
    live = fregistry.active()
    if plan is None:
        plan = live.plan if live is not None else None
    out = np.zeros((n_ticks, n_shards), np.int32)
    if plan is None or not any(
            r.point == "shard:straggle" for r in plan.rules):
        return out
    # quiet: fires reach telemetry exactly once via live.record()
    # below, so a restart's recompilation cannot duplicate them
    reg = fregistry.FaultRegistry(plan, quiet=True)
    for t in range(n_ticks):
        for k in range(n_shards):
            hit = reg.probe("shard:straggle")
            if hit is not None:
                _, arg = hit
                out[t, k] = int(arg if arg is not None
                                else fregistry.DEFAULT_STRAGGLE_UNITS)
    if live is not None and live.plan == plan:
        live.record(reg.fired)
    return out


def straggle_work(units, salt):
    """``units`` passes of a deterministic interference kernel over a
    (STRAGGLE_LANES,) f32 vector — the compiled-in straggler. ``units``
    may be a traced per-shard scalar (``lax.fori_loop`` takes a dynamic
    bound), so only the straggling shard pays; entangle the returned
    scalar with live state via ``lax.optimization_barrier`` so XLA
    cannot dead-code-eliminate the delay (the values are untouched —
    the barrier is an identity)."""
    import jax.numpy as jnp
    from jax import lax

    v0 = jnp.full((STRAGGLE_LANES,), jnp.float32(salt))

    def one(i, v):
        del i
        return v * jnp.float32(1.0000001) + jnp.float32(1e-7)

    # the raw sum feeds an optimization_barrier operand (entangle), so
    # the loop cannot be folded away; the value itself is never mixed
    # into any carried state
    return jnp.sum(lax.fori_loop(0, units, one, v0))


def entangle(state, dummy):
    """Tie ``dummy``'s computation into ``state``'s dependency chain
    without changing any value: the straggle work must be on the
    critical path of the carried state or the scheduler would hoist or
    drop it, and the measured delay with it."""
    from jax import lax

    out, _ = lax.optimization_barrier((state, dummy))
    return out


def staleness_weights(ages, active, took, decay: float):
    """Merge weights for one window: ``decay**age`` for the active
    shards that have a contribution, 0 for everyone else. ``ages`` is
    the per-shard contribution age in windows (0 = computed against the
    freshest merged model), replicated; the caller normalizes by the
    weight sum so the merge is a weighted average."""
    import jax.numpy as jnp

    w = jnp.asarray(decay, jnp.float32) ** ages.astype(jnp.float32)
    return jnp.where(active & took, w, 0.0)


def observed_staleness(ages_max, ages_mean) -> dict:
    """Host-side summary of the per-window age traces the SSP scan
    returns: the numbers the telemetry counters and ``tda report``'s
    SSP line carry."""
    am = np.asarray(ages_max)
    return {
        "max_staleness": int(am.max()) if am.size else 0,
        "mean_staleness": (float(np.asarray(ages_mean).mean())
                           if am.size else 0.0),
        "merges": int(am.size),
    }


def emit_ssp_counters(spec: SyncSpec, stats: dict, *,
                      straggle_ticks: int = 0, gated_ticks: int = 0,
                      epochs: int = 1) -> None:
    """Bump the ``ssp.*`` telemetry counters/gauges ``tda report``
    renders (a no-op when telemetry is disabled): merge count, observed
    max staleness, straggle/gated tick counts, membership epoch count,
    and the mean observed staleness as a gauge."""
    from tpu_distalg.telemetry import events as tevents

    # counts accumulate across a session's runs (totals are
    # meaningful); per-run EXTREMA and distribution stats ride gauges
    # (last run wins) — a counter-summed "max" across the chaos
    # harness's three trainings would misstate the observed bound
    tevents.counter("ssp.merges", stats.get("merges", 0))
    tevents.counter("ssp.straggle_ticks", straggle_ticks)
    tevents.counter("ssp.gated_ticks", gated_ticks)
    tevents.counter("ssp.membership_epochs", epochs)
    tevents.gauge("ssp.max_staleness", stats.get("max_staleness", 0))
    tevents.gauge("ssp.mean_staleness",
                  round(stats.get("mean_staleness", 0.0), 4))
    tevents.gauge("ssp.bound", spec.staleness)


def emit_stall_avoided(bsp_seconds: float, ssp_seconds: float,
                       n_ticks: int) -> float:
    """Record the measured stall time SSP avoided vs BSP over the same
    tick schedule (the bench's A/B is the honest observable — in-program
    estimates would be accounting, not measurement). Returns the ms
    figure fed to the ``ssp.stall_ms_avoided`` counter."""
    from tpu_distalg.telemetry import events as tevents

    ms = max(0.0, (bsp_seconds - ssp_seconds) * 1e3)
    tevents.counter("ssp.stall_ms_avoided", int(round(ms)))
    tevents.counter("ssp.stall_ticks_measured", n_ticks)
    return ms
