"""jax-version compatibility pinpoints (pre-0.6 spellings).

The ONE home for runtime-layer shims, so dropping support for old jax
is a single-file delete: every helper resolves the modern spelling
first and only falls back when it is absent.
"""

from __future__ import annotations

from jax import lax

try:
    from jax import shard_map as _shard_map_modern
except ImportError:  # pre-0.6: shard_map lives in jax.experimental
    _shard_map_modern = None
    from jax.experimental.shard_map import shard_map as _shard_map_legacy


def shard_map(fn, mesh, *, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` with the pre-0.6 fallback (where the
    replication check is spelled ``check_rep`` — same semantics).
    Defaults match jax's own (check on), so this is a drop-in
    replacement; opt out explicitly where the check is unwanted
    (``spmd.data_parallel`` does)."""
    if _shard_map_modern is not None:
        return _shard_map_modern(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    return _shard_map_legacy(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )


def axis_size(axis_name):
    """``lax.axis_size``, or the pre-0.6 idiom: psum of a literal folds
    to a static int under shard_map/pmap."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)
