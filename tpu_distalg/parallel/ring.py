"""Ring pipelines over the mesh data axis — sequence/context parallelism.

The reference has no sequences or attention (SURVEY.md §5: longest
"sequence" is a 31-feature row), but the communication layer of a TPU
framework must scale to long-context workloads (ring attention /
all-to-all sequence parallelism), so these are first-class here:

  * ``ring_allgather_matmul`` — A·Bᵀ where both operands are row-sharded:
    B blocks rotate around the ring (``ppermute`` over ICI) while partial
    products accumulate, overlapping communication with MXU compute — the
    standard ICI pipeline (cf. the scaling-book collective-matmul recipe).
  * ``ring_attention`` — exact blockwise attention with online softmax
    accumulation (Liu et al. ring attention; Milakov-Gimelshein online
    softmax): Q stays put, K/V blocks rotate; memory per chip is
    O(S_local²) instead of O(S²), so sequence length scales linearly with
    the ring size.

Both are shard_map bodies: run them inside ``data_parallel`` with
sequence-sharded operands.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from tpu_distalg.parallel.mesh import DATA_AXIS


def _ring_perm(n: int, shift: int = 1):
    return [(i, (i + shift) % n) for i in range(n)]


def ring_allgather_matmul(a_local, b_local, axis_name: str = DATA_AXIS):
    """Per-shard rows of A·Bᵀ with B row-sharded: (Sa_l, d) x (Sb, d)ᵀ.

    Each of the n ring steps multiplies the resident B block (MXU) while the
    next block is in flight (XLA overlaps the ppermute with the dot).
    Returns the (Sa_l, Sb) block of the full product owned by this shard.
    """
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    sb = b_local.shape[0]

    def body(i, carry):
        b, out = carry
        # the block currently resident came from shard (my - i) mod n
        src = (my - i) % n
        part = jnp.dot(a_local, b.T, preferred_element_type=jnp.float32)
        out = lax.dynamic_update_slice(out, part, (0, src * sb))
        b = lax.ppermute(b, axis_name, _ring_perm(n))
        return b, out

    out0 = jnp.zeros((a_local.shape[0], n * sb), dtype=jnp.float32)
    _, out = lax.fori_loop(0, n, body, (b_local, out0))
    return out


def ring_attention(q, k, v, axis_name: str = DATA_AXIS, *,
                   scale: float | None = None,
                   kv_chunk: int | None = None):
    """Exact attention over a sequence sharded around the ring.

    ``q, k, v``: (S_local, d) per shard. K/V blocks rotate; each arrival
    updates the online-softmax state (running max m, normalizer l,
    accumulator o) so the result is exactly ``softmax(QKᵀ/√d)·V`` over
    the FULL sequence.

    ``kv_chunk`` bounds the materialised score tile: the resident K/V
    block is processed in flash-attention-style chunks of that many keys
    (a ``lax.scan`` applying the same online-softmax update), so peak
    memory is O(S_local · kv_chunk) instead of O(S_local²) — at
    S_local = 32k a full score block is 4 GB and out of HBM, while
    kv_chunk = 1024 keeps it at 128 MB. ``None`` processes whole blocks
    (fine for short sequences; fewer, larger MXU calls).
    """
    n = lax.axis_size(axis_name)
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / (d ** 0.5)

    def online_update(o, m, l, kc, vc):
        scores = jnp.dot(q, kc.T, preferred_element_type=jnp.float32) * s
        m_new = jnp.maximum(m, jnp.max(scores, axis=-1))
        # rescale previous accumulator to the new max
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(scores - m_new[:, None])
        l = l * alpha + jnp.sum(p, axis=-1)
        o = o * alpha[:, None] + jnp.dot(
            p.astype(vc.dtype), vc, preferred_element_type=jnp.float32
        )
        return o, m_new, l

    s_local = k.shape[0]
    if kv_chunk is not None and (
        kv_chunk < 1 or (kv_chunk < s_local and s_local % kv_chunk)
    ):
        # kv_chunk >= s_local harmlessly degrades to whole-block
        # processing (the tile bound is already satisfied)
        raise ValueError(
            f"kv_chunk={kv_chunk} must be >= 1 and divide the local "
            f"K/V length {s_local}"
        )

    def process_block(kb, vb, o, m, l):
        if kv_chunk is None or kv_chunk >= s_local:
            return online_update(o, m, l, kb, vb)
        n_chunks = s_local // kv_chunk

        def chunk_step(carry, kv):
            kc, vc = kv
            return online_update(*carry, kc, vc), None

        (o, m, l), _ = lax.scan(
            chunk_step, (o, m, l),
            (kb.reshape(n_chunks, kv_chunk, d),
             vb.reshape(n_chunks, kv_chunk, d)),
        )
        return o, m, l

    def body(i, carry):
        kb, vb, o, m, l = carry
        o, m, l = process_block(kb, vb, o, m, l)
        kb = lax.ppermute(kb, axis_name, _ring_perm(n))
        vb = lax.ppermute(vb, axis_name, _ring_perm(n))
        return kb, vb, o, m, l

    o0 = jnp.zeros((q.shape[0], d), dtype=jnp.float32)
    m0 = jnp.full((q.shape[0],), -jnp.inf, dtype=jnp.float32)
    l0 = jnp.zeros((q.shape[0],), dtype=jnp.float32)
    _, _, o, _, l = lax.fori_loop(0, n, body, (k, v, o0, m0, l0))
    return o / l[:, None]


def alltoall_seq_to_head(x, axis_name: str = DATA_AXIS):
    """DeepSpeed-Ulysses-style exchange: (S_local, H, d) sequence-sharded →
    (S, H_local, d) head-sharded, in one all_to_all over the axis."""
    n = lax.axis_size(axis_name)
    s_l, h, d = x.shape
    if h % n:
        raise ValueError(
            f"alltoall_seq_to_head: head count {h} must be divisible by "
            f"the '{axis_name}' axis size {n}"
        )
    x = x.reshape(s_l, n, h // n, d)
    out = lax.all_to_all(x, axis_name, split_axis=1, concat_axis=0,
                         tiled=False)
    return out.reshape(n * s_l, h // n, d)
