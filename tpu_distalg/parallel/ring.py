"""Ring pipelines over the mesh data axis — sequence/context parallelism.

The reference has no sequences or attention (SURVEY.md §5: longest
"sequence" is a 31-feature row), but the communication layer of a TPU
framework must scale to long-context workloads (ring attention /
all-to-all sequence parallelism), so these are first-class here:

  * ``ring_allgather_matmul`` — A·Bᵀ where both operands are row-sharded:
    B blocks rotate around the ring (``ppermute`` over ICI) while partial
    products accumulate, overlapping communication with MXU compute — the
    standard ICI pipeline (cf. the scaling-book collective-matmul recipe).
  * ``ring_attention`` — exact blockwise attention with online softmax
    accumulation (Liu et al. ring attention; Milakov-Gimelshein online
    softmax): Q stays put, K/V blocks rotate; memory per chip is
    O(S_local²) instead of O(S²), so sequence length scales linearly with
    the ring size. Multi-head and causal decoding are supported — the
    full surface a decoder block needs.
  * ``ulysses_attention`` — DeepSpeed-Ulysses sequence parallelism: one
    ``all_to_all`` re-shards sequence→heads, every chip runs dense
    attention on its own heads over the FULL sequence, and the inverse
    ``all_to_all`` restores sequence sharding. Cheaper in collective
    volume than the ring when the head count divides the axis; the ring
    wins on peak memory (Ulysses materialises full-sequence K/V).

All are shard_map bodies: run them inside ``data_parallel`` with
sequence-sharded operands.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from tpu_distalg.parallel.mesh import DATA_AXIS
from tpu_distalg.parallel.compat import axis_size as _axis_size



def _ring_perm(n: int, shift: int = 1):
    return [(i, (i + shift) % n) for i in range(n)]


def ring_allgather_matmul(a_local, b_local, axis_name: str = DATA_AXIS):
    """Per-shard rows of A·Bᵀ with B row-sharded: (Sa_l, d) x (Sb, d)ᵀ.

    Each of the n ring steps multiplies the resident B block (MXU) while the
    next block is in flight (XLA overlaps the ppermute with the dot).
    Returns the (Sa_l, Sb) block of the full product owned by this shard.
    """
    n = _axis_size(axis_name)
    my = lax.axis_index(axis_name)
    sb = b_local.shape[0]

    def body(i, carry):
        b, out = carry
        # the block currently resident came from shard (my - i) mod n
        src = (my - i) % n
        part = jnp.dot(a_local, b.T, preferred_element_type=jnp.float32)
        out = lax.dynamic_update_slice(out, part, (0, src * sb))
        b = lax.ppermute(b, axis_name, _ring_perm(n))
        return b, out

    out0 = jnp.zeros((a_local.shape[0], n * sb), dtype=jnp.float32)
    _, out = lax.fori_loop(0, n, body, (b_local, out0))
    return out


def _online_update(qh, o, m, l, kh, vh, scale, mask):
    """One online-softmax accumulation step over a resident K/V chunk.

    ``qh``: (H, Sq, d); ``kh, vh``: (H_kv, C, d) with H divisible by
    H_kv — grouped-query KV heads are consumed through a zero-copy
    grouped einsum view (query heads [hk·g, hk·g+g) read KV head hk;
    no KV replication). State ``o``: (H, Sq, d), ``m, l``: (H, Sq).
    ``mask``: (Sq, C) boolean (True = attend) or None. Fully-masked
    rows are handled safely: while ``m`` is still −inf the rescale
    factor and probabilities are forced to 0 instead of
    exp(−inf − −inf) = NaN.
    """
    h, s_q, d_ = qh.shape
    h_kv, c = kh.shape[0], kh.shape[1]
    g = h // h_kv
    if g == 1:
        scores = jnp.einsum(
            "hqd,hkd->hqk", qh, kh, preferred_element_type=jnp.float32
        ) * scale
    else:
        scores = jnp.einsum(
            "hgqd,hkd->hgqk", qh.reshape(h_kv, g, s_q, d_), kh,
            preferred_element_type=jnp.float32,
        ).reshape(h, s_q, c) * scale
    if mask is not None:
        scores = jnp.where(mask[None], scores, -jnp.inf)
    m_new = jnp.maximum(m, jnp.max(scores, axis=-1))
    safe = ~jnp.isneginf(m_new)
    alpha = jnp.where(safe, jnp.exp(m - m_new), 0.0)
    p = jnp.where(
        safe[..., None], jnp.exp(scores - m_new[..., None]), 0.0
    )
    l = l * alpha + jnp.sum(p, axis=-1)
    pv = p.astype(vh.dtype)
    if g == 1:
        upd = jnp.einsum("hqk,hkd->hqd", pv, vh,
                         preferred_element_type=jnp.float32)
    else:
        upd = jnp.einsum(
            "hgqk,hkd->hgqd", pv.reshape(h_kv, g, s_q, c), vh,
            preferred_element_type=jnp.float32,
        ).reshape(h, s_q, d_)
    o = o * alpha[..., None] + upd
    return o, m_new, l


def zigzag_order(n_shards: int, n_rows: int):
    """Row permutation for the balanced causal ring layout: lay a
    global (S, ...) array out as ``x[zigzag_order(n, S)]`` and shard it
    over the ring; shard s then holds global chunks (s, 2n−1−s) — the
    position↔shard map :func:`ring_attention` ``layout='zigzag'``
    expects. ``S`` must divide into 2n equal chunks."""
    if n_rows % (2 * n_shards):
        raise ValueError(
            f"zigzag_order: {n_rows} rows not divisible by "
            f"2·n_shards={2 * n_shards}"
        )
    import numpy as np

    c = n_rows // (2 * n_shards)
    parts = []
    for s in range(n_shards):
        parts.append(np.arange(s * c, (s + 1) * c))
        parts.append(np.arange((2 * n_shards - 1 - s) * c,
                               (2 * n_shards - s) * c))
    return np.concatenate(parts)


def zigzag_inverse(n_shards: int, n_rows: int):
    """Inverse permutation: ``out[zigzag_order] = zigzag_out`` →
    ``zigzag_out[zigzag_inverse]`` is in natural position order."""
    import numpy as np

    p = zigzag_order(n_shards, n_rows)
    inv = np.empty_like(p)
    inv[p] = np.arange(len(p))
    return inv


def ring_attention(q, k, v, axis_name: str = DATA_AXIS, *,
                   scale: float | None = None,
                   kv_chunk: int | None = None,
                   causal: bool = False,
                   use_flash: bool = False,
                   flash_interpret: bool = False,
                   flash_block_q: int = 2048,
                   flash_block_kv: int = 2048,
                   layout: str = "contiguous"):
    """Exact attention over a sequence sharded around the ring.

    ``q, k, v``: (S_local, d) single-head or (S_local, H, d) multi-head
    per shard, sequence-sharded in ring order (shard i holds global
    positions [i·S_local, (i+1)·S_local)). K/V blocks rotate; each
    arrival updates the online-softmax state (running max m, normalizer
    l, accumulator o) so the result is exactly ``softmax(QKᵀ/√d)·V`` over
    the FULL sequence, per head.

    ``causal=True`` applies the decoder mask on GLOBAL positions: query
    p attends to keys ≤ p. Blocks that arrive from a later shard are
    fully masked and skipped outright (``lax.cond`` around the compute —
    the ppermute still runs, keeping the ring in lockstep). The skip
    saves the FLOPs but not the wall-clock imbalance: shard n−1 computes
    n partial blocks while shard 0 computes 1, idling ~half the ring's
    FLOP capacity at n=8. ``layout='zigzag'`` fixes that: each shard
    holds global chunks (s, 2n−1−s) — lay data out with
    :func:`zigzag_order` / undo with :func:`zigzag_inverse` — and each
    ring step decomposes into chunk-pairs of which ONE is statically
    all-attend, one statically skipped, and two conditional, so every
    shard computes exactly 2n+1 chunk-pair tiles (≈2n·c² FLOPs, c the
    half-chunk length) per pass REGARDLESS of position — vs the
    contiguous layout's shard-dependent 1…n full blocks (the striped/
    zigzag context-parallel schedule; cf. llama-3-style zigzag
    sharding). Zigzag requires ``causal=True`` (balanced already when
    non-causal), even local length, and supersedes ``kv_chunk`` (use
    flash blocks to bound memory).

    ``kv_chunk`` bounds the materialised score tile: the resident K/V
    block is processed in flash-attention-style chunks of that many keys
    (a ``lax.scan`` applying the same online-softmax update), so peak
    memory is O(S_local · kv_chunk) per head instead of O(S_local²) — at
    S_local = 32k a full score block is 4 GB and out of HBM, while
    kv_chunk = 1024 keeps it at 128 MB. ``None`` processes whole blocks
    (fine for short sequences; fewer, larger MXU calls).

    ``use_flash=True`` swaps the XLA update for the Pallas flash kernel
    (``ops.pallas_attention.flash_attention_block``): the whole
    QKᵀ→softmax→·V pipeline runs per VMEM-resident tile — same algebra
    and f32 accumulation, much less HBM traffic. Needs head-dim a
    multiple of 128 and block-divisible lengths, supersedes
    ``kv_chunk``. DIFFERENTIABLE end-to-end at flash speed: the custom
    VJP saves (O, logsumexp) from the forward ring and runs a SECOND
    ring of Pallas backward kernels
    (``ops.pallas_attention.flash_attention_backward_block``) — K/V
    blocks rotate again, each step recomputes P from the saved stats
    per VMEM tile and emits (dQ partial, dK/dV of the resident block);
    the dK/dV accumulators travel WITH their blocks so after n steps
    each shard holds its own finished cotangent. Same algebra and f32
    accumulation as differentiating the XLA path, so the gradients are
    exact. Set ``flash_interpret=True`` on CPU meshes (tests).
    """
    if layout not in ("contiguous", "zigzag"):
        raise ValueError(f"unknown ring layout {layout!r}")
    if layout == "zigzag":
        if not causal:
            raise ValueError(
                "layout='zigzag' exists to balance the CAUSAL ring; "
                "non-causal rings are balanced already"
            )
        if kv_chunk is not None:
            raise ValueError(
                "layout='zigzag' does not compose with kv_chunk; use "
                "use_flash=True (tiled in VMEM) to bound memory"
            )
        return _ring_attention_zigzag(
            q, k, v, axis_name=axis_name, scale=scale,
            use_flash=use_flash, flash_interpret=flash_interpret,
            bq=flash_block_q, bkv=flash_block_kv,
        )
    if use_flash:
        from tpu_distalg.ops.pallas_attention import BWD_BLOCK_MAX

        bwd_bq = min(flash_block_q, BWD_BLOCK_MAX)
        bwd_bkv = min(flash_block_kv, BWD_BLOCK_MAX)
        impl = functools.partial(
            _ring_attention_impl, axis_name=axis_name, scale=scale,
            kv_chunk=kv_chunk, causal=causal,
            flash_interpret=flash_interpret,
            flash_block_q=flash_block_q, flash_block_kv=flash_block_kv,
        )

        @jax.custom_vjp
        def flash_fn(q, k, v):
            return impl(q, k, v, use_flash=True)

        def _fwd(q, k, v):
            out, lse = impl(q, k, v, use_flash=True, return_stats=True)
            return out, (q, k, v, out, lse)

        def _bwd(res, g):
            qq, kk, vv, out, lse = res
            return _ring_flash_backward(
                qq, kk, vv, out, lse, g, axis_name=axis_name,
                scale=scale, causal=causal,
                flash_interpret=flash_interpret,
                bq=bwd_bq, bkv=bwd_bkv,
            )

        flash_fn.defvjp(_fwd, _bwd)
        return flash_fn(q, k, v)
    return _ring_attention_impl(
        q, k, v, axis_name=axis_name, scale=scale, kv_chunk=kv_chunk,
        causal=causal, use_flash=False,
        flash_interpret=flash_interpret,
        flash_block_q=flash_block_q, flash_block_kv=flash_block_kv,
    )


def _ring_flash_backward(q, k, v, out, lse, g, *, axis_name, scale,
                         causal, flash_interpret, bq, bkv):
    """Ring of flash backward kernels — dK/dV accumulators ride along.

    Forward residuals: ``out`` (normalised, f32, caller layout) and
    ``lse`` (H, S_q, 1) — the FINAL ring-wide logsumexp, so every
    backward tile recomputes the true softmax P independently; no
    rescaling chain crosses ring steps. Each of the n steps feeds the
    resident K/V block and ITS travelling (dk, dv) accumulator through
    ``flash_attention_backward_block``; dQ accumulates locally. The
    rotation count is n, so every (block, accumulator) pair ends the
    loop back on its owner shard. Comm volume is 2× the forward ring
    (4 rotating buffers) — the standard ring-attention backward cost.
    """
    from tpu_distalg.ops.pallas_attention import (
        flash_attention_backward_block,
    )

    single = q.ndim == 2
    if single:
        q, k, v, out, g = (x[:, None, :] for x in (q, k, v, out, g))
    n = _axis_size(axis_name)
    my = lax.axis_index(axis_name)
    s_q, h, d = q.shape
    s_local = k.shape[0]
    s = scale if scale is not None else 1.0 / (d ** 0.5)
    qh = jnp.moveaxis(q, 1, 0)                        # (H, Sq, d)
    kh0 = jnp.moveaxis(k, 1, 0)                       # (H_kv, Sl, d)
    vh0 = jnp.moveaxis(v, 1, 0)
    doh = jnp.moveaxis(g, 1, 0).astype(jnp.float32)
    oh = jnp.moveaxis(out, 1, 0).astype(jnp.float32)
    delta = jnp.sum(doh * oh, axis=-1, keepdims=True)  # (H, Sq, 1)

    def body(i, carry):
        kh, vh, dk, dv, dq = carry
        src = (my - i) % n

        def compute(args):
            dq, dk, dv = args
            dq_c, dk_c, dv_c = flash_attention_backward_block(
                qh, kh, vh, doh, lse, delta,
                my * s_q, src * s_local, scale=s, causal=causal,
                bq=bq, bkv=bkv, interpret=flash_interpret,
            )
            return dq + dq_c, dk + dk_c, dv + dv_c

        if causal:
            dq, dk, dv = lax.cond(
                src <= my, compute, lambda a: a, (dq, dk, dv))
        else:
            dq, dk, dv = compute((dq, dk, dv))
        perm = _ring_perm(n)
        kh = lax.ppermute(kh, axis_name, perm)
        vh = lax.ppermute(vh, axis_name, perm)
        dk = lax.ppermute(dk, axis_name, perm)
        dv = lax.ppermute(dv, axis_name, perm)
        return kh, vh, dk, dv, dq

    zeros = functools.partial(jnp.zeros, dtype=jnp.float32)
    _, _, dk, dv, dq = lax.fori_loop(
        0, n, body,
        (kh0, vh0, zeros(kh0.shape), zeros(vh0.shape),
         zeros((h, s_q, d))),
    )
    dq = jnp.moveaxis(dq, 0, 1).astype(q.dtype)
    dk = jnp.moveaxis(dk, 0, 1).astype(k.dtype)
    dv = jnp.moveaxis(dv, 0, 1).astype(v.dtype)
    if single:
        dq, dk, dv = (x[:, 0, :] for x in (dq, dk, dv))
    return dq, dk, dv


def _ring_attention_zigzag(q, k, v, *, axis_name, scale, use_flash,
                           flash_interpret, bq, bkv):
    if not use_flash:
        return _zigzag_impl(
            q, k, v, axis_name=axis_name, scale=scale, use_flash=False,
            flash_interpret=flash_interpret, bq=bq, bkv=bkv)
    impl = functools.partial(
        _zigzag_impl, axis_name=axis_name, scale=scale,
        flash_interpret=flash_interpret, bq=bq, bkv=bkv)

    @jax.custom_vjp
    def flash_fn(q, k, v):
        return impl(q, k, v, use_flash=True)

    def _fwd(q, k, v):
        out, lse = impl(q, k, v, use_flash=True, return_stats=True)
        return out, (q, k, v, out, lse)

    def _bwd(res, g):
        from tpu_distalg.ops.pallas_attention import BWD_BLOCK_MAX

        qq, kk, vv, out, lse = res
        return _zigzag_flash_backward(
            qq, kk, vv, out, lse, g, axis_name=axis_name, scale=scale,
            flash_interpret=flash_interpret,
            bq=min(bq, BWD_BLOCK_MAX), bkv=min(bkv, BWD_BLOCK_MAX))

    flash_fn.defvjp(_fwd, _bwd)
    return flash_fn(q, k, v)


def _zigzag_pairs(my, src, n, c):
    """Global start offsets of the per-step chunk-pairs.

    Shard s holds q/k chunks (s, 2n−1−s) of c rows each. Of the four
    (q-chunk, kv-chunk) pairs per ring step, (C,B) is STATICALLY all-
    masked (C = my ≤ n−1 < B = 2n−1−src) and (D,A) STATICALLY all-
    attend (D = 2n−1−my ≥ n > A = src), leaving two conditional pairs.
    Per full pass shard ``my`` computes n unconditional (D,A) pairs,
    my+1 (C,A) pairs and n−my (D,B) pairs = 2n+1 c²-tiles for EVERY
    shard (≈2n·c² FLOPs after the triangular pairs' tile skip) — the
    balance the contiguous layout lacks.
    """
    qc0 = my * c
    qd0 = (2 * n - 1 - my) * c
    ka0 = src * c
    kb0 = (2 * n - 1 - src) * c
    return qc0, qd0, ka0, kb0


def _zigzag_impl(q, k, v, *, axis_name, scale, use_flash,
                 flash_interpret, bq, bkv, return_stats=False):
    single = q.ndim == 2
    if single:
        q, k, v = (x[:, None, :] for x in (q, k, v))
    n = _axis_size(axis_name)
    my = lax.axis_index(axis_name)
    s_q, h, d = q.shape
    if s_q % 2 or k.shape[0] != s_q:
        raise ValueError(
            f"zigzag ring: local length {s_q} must be even (two "
            f"chunks) and q/k lengths equal (got k {k.shape[0]})"
        )
    if h % k.shape[1]:
        raise ValueError(
            f"ring_attention: {h} query heads not divisible by "
            f"{k.shape[1]} KV heads"
        )
    c = s_q // 2
    s = scale if scale is not None else 1.0 / (d ** 0.5)
    qh = jnp.moveaxis(q, 1, 0)                     # (H, 2c, d)
    qhC, qhD = qh[:, :c], qh[:, c:]

    if use_flash:
        from tpu_distalg.ops.pallas_attention import flash_attention_block

        def upd(qc, kc, vc, st, q0, k0, causal_pair):
            o, m, l = st
            o, m, l = flash_attention_block(
                qc, kc, vc, o, m[..., None], l[..., None], q0, k0,
                scale=s, causal=causal_pair, bq=bq, bkv=bkv,
                interpret=flash_interpret)
            return o, m[..., 0], l[..., 0]
    else:
        def upd(qc, kc, vc, st, q0, k0, causal_pair):
            mask = None
            if causal_pair:
                mask = ((q0 + jnp.arange(c))[:, None]
                        >= (k0 + jnp.arange(c))[None, :])
            return _online_update(qc, *st, kc, vc, s, mask)

    def body(i, carry):
        kh, vh, stC, stD = carry
        src = (my - i) % n
        qc0, qd0, ka0, kb0 = _zigzag_pairs(my, src, n, c)
        kA, vA = kh[:, :c], vh[:, :c]
        kB, vB = kh[:, c:], vh[:, c:]
        stC = lax.cond(
            src <= my,
            lambda st: upd(qhC, kA, vA, st, qc0, ka0, True),
            lambda st: st, stC)
        stD = upd(qhD, kA, vA, stD, qd0, ka0, False)
        stD = lax.cond(
            src >= my,
            lambda st: upd(qhD, kB, vB, st, qd0, kb0, True),
            lambda st: st, stD)
        perm = _ring_perm(n)
        return (lax.ppermute(kh, axis_name, perm),
                lax.ppermute(vh, axis_name, perm), stC, stD)

    def st0():
        return (jnp.zeros((h, c, d), jnp.float32),
                jnp.full((h, c), -jnp.inf, jnp.float32),
                jnp.zeros((h, c), jnp.float32))

    kh0 = jnp.moveaxis(k, 1, 0)
    vh0 = jnp.moveaxis(v, 1, 0)
    _, _, (oC, mC, lC), (oD, mD, lD) = lax.fori_loop(
        0, n, body, (kh0, vh0, st0(), st0()))
    o = jnp.concatenate([oC / lC[..., None], oD / lD[..., None]],
                        axis=1)
    out = jnp.moveaxis(o, 0, 1)                    # (2c, H, d)
    out = out[:, 0, :] if single else out
    if return_stats:
        lse = jnp.concatenate(
            [mC + jnp.log(lC), mD + jnp.log(lD)], axis=1)[..., None]
        return out, lse
    return out


def _zigzag_flash_backward(q, k, v, out, lse, g, *, axis_name, scale,
                           flash_interpret, bq, bkv):
    """Zigzag mirror of :func:`_ring_flash_backward`: the same three
    live chunk-pairs per step, dK/dV accumulators rotating with their
    blocks, dQ accumulating per local chunk."""
    from tpu_distalg.ops.pallas_attention import (
        flash_attention_backward_block,
    )

    single = q.ndim == 2
    if single:
        q, k, v, out, g = (x[:, None, :] for x in (q, k, v, out, g))
    n = _axis_size(axis_name)
    my = lax.axis_index(axis_name)
    s_q, h, d = q.shape
    c = s_q // 2
    s = scale if scale is not None else 1.0 / (d ** 0.5)
    qh = jnp.moveaxis(q, 1, 0)
    kh0 = jnp.moveaxis(k, 1, 0)
    vh0 = jnp.moveaxis(v, 1, 0)
    doh = jnp.moveaxis(g, 1, 0).astype(jnp.float32)
    oh = jnp.moveaxis(out, 1, 0).astype(jnp.float32)
    delta = jnp.sum(doh * oh, axis=-1, keepdims=True)  # (H, 2c, 1)
    qhC, qhD = qh[:, :c], qh[:, c:]
    doC, doD = doh[:, :c], doh[:, c:]
    lseC, lseD = lse[:, :c], lse[:, c:]
    dC, dD = delta[:, :c], delta[:, c:]

    def pair_bwd(qc, kc, vc, do_c, lse_c, delta_c, q0, k0, causal_pair):
        return flash_attention_backward_block(
            qc, kc, vc, do_c, lse_c, delta_c, q0, k0, scale=s,
            causal=causal_pair, bq=bq, bkv=bkv,
            interpret=flash_interpret)

    def body(i, carry):
        kh, vh, dk, dv, dqC, dqD = carry
        src = (my - i) % n
        qc0, qd0, ka0, kb0 = _zigzag_pairs(my, src, n, c)
        kA, vA = kh[:, :c], vh[:, :c]
        kB, vB = kh[:, c:], vh[:, c:]

        def ca(args):
            dqC, dk, dv = args
            dq_c, dk_c, dv_c = pair_bwd(qhC, kA, vA, doC, lseC, dC,
                                        qc0, ka0, True)
            return (dqC + dq_c, dk.at[:, :c].add(dk_c),
                    dv.at[:, :c].add(dv_c))

        dqC, dk, dv = lax.cond(
            src <= my, ca, lambda a: a, (dqC, dk, dv))
        dq_c, dk_c, dv_c = pair_bwd(qhD, kA, vA, doD, lseD, dD,
                                    qd0, ka0, False)
        dqD = dqD + dq_c
        dk = dk.at[:, :c].add(dk_c)
        dv = dv.at[:, :c].add(dv_c)

        def db(args):
            dqD, dk, dv = args
            dq_c, dk_c, dv_c = pair_bwd(qhD, kB, vB, doD, lseD, dD,
                                        qd0, kb0, True)
            return (dqD + dq_c, dk.at[:, c:].add(dk_c),
                    dv.at[:, c:].add(dv_c))

        dqD, dk, dv = lax.cond(
            src >= my, db, lambda a: a, (dqD, dk, dv))
        perm = _ring_perm(n)
        return (lax.ppermute(kh, axis_name, perm),
                lax.ppermute(vh, axis_name, perm),
                lax.ppermute(dk, axis_name, perm),
                lax.ppermute(dv, axis_name, perm), dqC, dqD)

    zeros = functools.partial(jnp.zeros, dtype=jnp.float32)
    _, _, dk, dv, dqC, dqD = lax.fori_loop(
        0, n, body,
        (kh0, vh0, zeros(kh0.shape), zeros(vh0.shape),
         zeros((h, c, d)), zeros((h, c, d))))
    dq = jnp.concatenate([dqC, dqD], axis=1)
    dq = jnp.moveaxis(dq, 0, 1).astype(q.dtype)
    dk = jnp.moveaxis(dk, 0, 1).astype(k.dtype)
    dv = jnp.moveaxis(dv, 0, 1).astype(v.dtype)
    if single:
        dq, dk, dv = (x[:, 0, :] for x in (dq, dk, dv))
    return dq, dk, dv


def _ring_attention_impl(q, k, v, *, axis_name, scale, kv_chunk,
                         causal, use_flash, flash_interpret,
                         flash_block_q, flash_block_kv,
                         return_stats=False):
    single = q.ndim == 2
    if single:
        q, k, v = (x[:, None, :] for x in (q, k, v))
    n = _axis_size(axis_name)
    my = lax.axis_index(axis_name)
    s_q, h, d = q.shape
    if h % k.shape[1]:
        raise ValueError(
            f"ring_attention: {h} query heads not divisible by "
            f"{k.shape[1]} KV heads"
        )
    s = scale if scale is not None else 1.0 / (d ** 0.5)
    qh = jnp.moveaxis(q, 1, 0)                     # (H, Sq, d)
    s_local = k.shape[0]
    if not use_flash and kv_chunk is not None and (
        kv_chunk < 1 or (kv_chunk < s_local and s_local % kv_chunk)
    ):
        # kv_chunk >= s_local harmlessly degrades to whole-block
        # processing (the tile bound is already satisfied); the flash
        # kernel tiles internally and never reads kv_chunk
        raise ValueError(
            f"kv_chunk={kv_chunk} must be >= 1 and divide the local "
            f"K/V length {s_local}"
        )
    q_pos = my * s_q + jnp.arange(s_q)             # global query positions

    if use_flash:
        from tpu_distalg.ops.pallas_attention import flash_attention_block

        def process_block(kh, vh, o, m, l, src):
            o, m, l = flash_attention_block(
                qh, kh, vh, o, m[..., None], l[..., None],
                my * s_q, src * s_local, scale=s, causal=causal,
                bq=flash_block_q, bkv=flash_block_kv,
                interpret=flash_interpret,
            )
            return o, m[..., 0], l[..., 0]
    else:
        def process_block(kh, vh, o, m, l, src):
            # kh, vh: (H_kv, S_local, d) — transposed ONCE before the
            # ring loop; ppermute commutes with the transpose, so
            # blocks rotate in this layout and no per-ring-step
            # relayout is paid. Grouped-query KV heads are consumed by
            # _online_update's grouped einsum view — the ring moves and
            # the update reads only H_kv heads, no replication
            if kv_chunk is None or kv_chunk >= s_local:
                mask = None
                if causal:
                    k_pos = src * s_local + jnp.arange(s_local)
                    mask = q_pos[:, None] >= k_pos[None, :]
                return _online_update(qh, o, m, l, kh, vh, s, mask)
            n_chunks = s_local // kv_chunk
            h_kv = kh.shape[0]
            kc = kh.reshape(h_kv, n_chunks, kv_chunk, d).transpose(
                1, 0, 2, 3)
            vc = vh.reshape(h_kv, n_chunks, kv_chunk, d).transpose(
                1, 0, 2, 3)

            def chunk_step(carry, xs):
                kcc, vcc, c = xs
                mask = None
                if causal:
                    k_pos = (src * s_local + c * kv_chunk
                             + jnp.arange(kv_chunk))
                    mask = q_pos[:, None] >= k_pos[None, :]
                return _online_update(qh, *carry, kcc, vcc, s, mask), None

            (o, m, l), _ = lax.scan(
                chunk_step, (o, m, l), (kc, vc, jnp.arange(n_chunks))
            )
            return o, m, l

    def body(i, carry):
        kh, vh, o, m, l = carry
        # the block currently resident came from shard (my - i) mod n
        src = (my - i) % n
        if causal:
            o, m, l = lax.cond(
                src <= my,
                lambda oml: process_block(kh, vh, *oml, src),
                lambda oml: oml,
                (o, m, l),
            )
        else:
            o, m, l = process_block(kh, vh, o, m, l, src)
        kh = lax.ppermute(kh, axis_name, _ring_perm(n))
        vh = lax.ppermute(vh, axis_name, _ring_perm(n))
        return kh, vh, o, m, l

    o0 = jnp.zeros((h, s_q, d), dtype=jnp.float32)
    m0 = jnp.full((h, s_q), -jnp.inf, dtype=jnp.float32)
    l0 = jnp.zeros((h, s_q), dtype=jnp.float32)
    kh0 = jnp.moveaxis(k, 1, 0)                    # (H, S_local, d)
    vh0 = jnp.moveaxis(v, 1, 0)
    _, _, o, m, l = lax.fori_loop(0, n, body, (kh0, vh0, o0, m0, l0))
    out = jnp.moveaxis(o / l[..., None], 0, 1)     # (Sq, H, d)
    out = out[:, 0, :] if single else out
    if return_stats:
        # final ring-wide logsumexp per row, (H, Sq, 1) — the flash
        # backward's recompute anchor
        return out, (m + jnp.log(l))[..., None]
    return out


def softmax_attention(q, k, v, *, scale: float | None = None,
                      causal: bool = False, use_flash: bool = False,
                      flash_interpret: bool = False):
    """Dense reference attention, (S, H, d) × (T, H, d) → (S, H, d).

    Materialises the full (H, S, T) score tensor — the local compute of
    :func:`ulysses_attention` and the oracle the ring variants are tested
    against. ``use_flash=True`` runs the Pallas flash kernel instead
    (tiled, no (H, S, T) materialisation) — DIFFERENTIABLE via the same
    flash backward kernels as the ring path (one "ring step" with both
    offsets 0), so Ulysses-flash trains at flash speed too.
    """
    d = q.shape[-1]
    if q.shape[1] % k.shape[1]:
        raise ValueError(
            f"softmax_attention: {q.shape[1]} query heads not "
            f"divisible by {k.shape[1]} KV heads"
        )
    s = scale if scale is not None else 1.0 / (d ** 0.5)
    if use_flash:
        from tpu_distalg.ops.pallas_attention import (
            flash_attention_backward_block,
            flash_attention_block,
        )

        def _flash_fwd_stats(q_, k_, v_):
            qh = jnp.moveaxis(q_, 1, 0)               # (H, S, d)
            h, s_q, _ = qh.shape
            o, m, l = flash_attention_block(
                qh, jnp.moveaxis(k_, 1, 0), jnp.moveaxis(v_, 1, 0),
                jnp.zeros((h, s_q, d), jnp.float32),
                jnp.full((h, s_q, 1), -jnp.inf, jnp.float32),
                jnp.zeros((h, s_q, 1), jnp.float32),
                0, 0, scale=s, causal=causal, interpret=flash_interpret,
            )
            return jnp.moveaxis(o / l, 0, 1), m + jnp.log(l)

        @jax.custom_vjp
        def flash_fn(q_, k_, v_):
            return _flash_fwd_stats(q_, k_, v_)[0]

        def _fwd(q_, k_, v_):
            out, lse = _flash_fwd_stats(q_, k_, v_)
            return out, (q_, k_, v_, out, lse)

        def _bwd(res, g):
            q_, k_, v_, out, lse = res
            doh = jnp.moveaxis(g, 1, 0).astype(jnp.float32)
            oh = jnp.moveaxis(out, 1, 0).astype(jnp.float32)
            delta = jnp.sum(doh * oh, axis=-1, keepdims=True)
            dq, dk, dv = flash_attention_backward_block(
                jnp.moveaxis(q_, 1, 0), jnp.moveaxis(k_, 1, 0),
                jnp.moveaxis(v_, 1, 0), doh, lse, delta, 0, 0,
                scale=s, causal=causal, interpret=flash_interpret,
            )
            return (jnp.moveaxis(dq, 0, 1).astype(q_.dtype),
                    jnp.moveaxis(dk, 0, 1).astype(k_.dtype),
                    jnp.moveaxis(dv, 0, 1).astype(v_.dtype))

        flash_fn.defvjp(_fwd, _bwd)
        return flash_fn(q, k, v)
    # grouped-query heads consumed through a zero-copy grouped einsum
    # view, like _online_update — no KV replication on any path
    s_q, h, _ = q.shape
    t, h_kv = k.shape[0], k.shape[1]
    g = h // h_kv
    scores = jnp.einsum(
        "qhgd,khd->hgqk", q.reshape(s_q, h_kv, g, d), k,
        preferred_element_type=jnp.float32,
    ).reshape(h, s_q, t) * s
    if causal:
        mask = jnp.arange(s_q)[:, None] >= jnp.arange(t)[None, :]
        scores = jnp.where(mask[None], scores, -jnp.inf)
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum(
        "hgqk,khd->qhgd", p.astype(v.dtype).reshape(h_kv, g, s_q, t), v,
        preferred_element_type=jnp.float32,
    ).reshape(s_q, h, d)


def ulysses_attention(q, k, v, axis_name: str = DATA_AXIS, *,
                      scale: float | None = None, causal: bool = False,
                      use_flash: bool = False,
                      flash_interpret: bool = False):
    """DeepSpeed-Ulysses sequence-parallel attention.

    ``q, k, v``: (S_local, H, d) sequence-sharded. One ``all_to_all``
    re-shards to (S, H_local, d) — every chip holds the FULL sequence for
    H/n of the heads — attention runs locally per head (positions
    are global, so ``causal`` needs no cross-shard bookkeeping), and the
    inverse exchange restores (S_local, H, d). Exact; requires H
    divisible by the axis size. ``use_flash=True`` runs the local
    attention through the Pallas flash kernel (no full score tensor),
    DIFFERENTIABLE via :func:`softmax_attention`'s flash VJP — the
    cotangents flow back through the inverse exchanges; otherwise peak
    memory is O(S²·H/n) — prefer :func:`ring_attention` when that
    binds.
    """
    qh = alltoall_seq_to_head(q, axis_name)
    kh = alltoall_seq_to_head(k, axis_name)
    vh = alltoall_seq_to_head(v, axis_name)
    o = softmax_attention(qh, kh, vh, scale=scale, causal=causal,
                          use_flash=use_flash,
                          flash_interpret=flash_interpret)
    return alltoall_head_to_seq(o, axis_name)


def _seq_to_head_impl(x, axis_name):
    n = _axis_size(axis_name)
    s_l, h, d = x.shape
    if h % n:
        raise ValueError(
            f"alltoall_seq_to_head: head count {h} must be divisible by "
            f"the '{axis_name}' axis size {n}"
        )
    x = x.reshape(s_l, n, h // n, d)
    out = lax.all_to_all(x, axis_name, split_axis=1, concat_axis=0,
                         tiled=False)
    return out.reshape(n * s_l, h // n, d)


def _head_to_seq_impl(x, axis_name):
    n = _axis_size(axis_name)
    s, h_l, d = x.shape
    if s % n:
        raise ValueError(
            f"alltoall_head_to_seq: sequence length {s} must be "
            f"divisible by the '{axis_name}' axis size {n}"
        )
    x = x.reshape(n, s // n, h_l, d)
    out = lax.all_to_all(x, axis_name, split_axis=0, concat_axis=1,
                         tiled=False)
    return out.reshape(s // n, n * h_l, d)


# Both exchanges are global orthogonal permutations, so each one's VJP
# is exactly the inverse exchange — declared via custom_vjp because the
# automatic transpose of all_to_all(tiled=False) through the enclosing
# reshapes currently fails Mosaic/XLA verification under shard_map.

@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def alltoall_seq_to_head(x, axis_name: str = DATA_AXIS):
    """DeepSpeed-Ulysses-style exchange: (S_local, H, d) sequence-sharded →
    (S, H_local, d) head-sharded, in one all_to_all over the axis."""
    return _seq_to_head_impl(x, axis_name)


alltoall_seq_to_head.defvjp(
    lambda x, axis_name: (_seq_to_head_impl(x, axis_name), None),
    lambda axis_name, _, g: (_head_to_seq_impl(g, axis_name),),
)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def alltoall_head_to_seq(x, axis_name: str = DATA_AXIS):
    """Inverse of :func:`alltoall_seq_to_head`: (S, H_local, d)
    head-sharded → (S_local, H, d) sequence-sharded, in one all_to_all.
    ``alltoall_head_to_seq(alltoall_seq_to_head(x))`` is the identity."""
    return _head_to_seq_impl(x, axis_name)


alltoall_head_to_seq.defvjp(
    lambda x, axis_name: (_head_to_seq_impl(x, axis_name), None),
    lambda axis_name, _, g: (_seq_to_head_impl(g, axis_name),),
)
