"""Per-shard SPMD execution — the mapPartitions replacement.

The reference's per-partition compute (``mapPartitions(WithIndex)``, e.g.
``/root/reference/optimization/ma.py:84-87``) maps onto ``jax.shard_map``:
the body function sees the local block of each sharded operand and may call
collectives. ``replica_index`` is the analogue of the partition index that
``mapPartitionsWithIndex`` passes in.
"""

from __future__ import annotations

from jax import lax
from jax.sharding import Mesh

from tpu_distalg.parallel.mesh import DATA_AXIS


def replica_index(axis_name: str = DATA_AXIS):
    """Index of this shard along the axis (≙ the mapPartitionsWithIndex idx)."""
    return lax.axis_index(axis_name)


def data_parallel(fn, mesh: Mesh, *, in_specs, out_specs,
                  check_vma: bool = False):
    """Wrap ``fn`` as a shard_map over the mesh.

    ``in_specs``/``out_specs`` are PartitionSpecs; pass ``P('data')`` for
    RDD-like row-sharded operands and ``P()`` for broadcast (replicated)
    operands — mirroring exactly which reference values travelled via
    ``parallelize`` vs ``broadcast``.
    """
    from tpu_distalg.parallel.compat import shard_map

    return shard_map(
        fn, mesh, in_specs=in_specs, out_specs=out_specs,
        check_vma=check_vma,
    )
