"""Partition-rule engine — one rule table for every model's placement,
and device-side resharding between layouts.

Two halves (ROADMAP item 5):

  * **Rule engine.** Sharding decisions used to be hand-rolled per
    model (the ssgd tp matvec, ALS model-axis padding, the
    feature-sharded variants, every SSP carry re-put). Here a model's
    placement is a :class:`RuleTable` — an ordered list of
    ``(regex, PartitionSpec)`` rules matched against *named* pytree
    leaves (paths joined with ``/``) — from which the engine generates
    the shard/place/gather functions. Scalars are always replicated;
    a leaf no rule matches is a HARD error (a silently-replicated new
    leaf is exactly the drift this engine exists to kill). Every
    model registers its table here, so a 2-D ``data × model`` mesh is
    a ``--mesh-shape`` config, not a code path, and lint rule TDA080
    (``analysis/partition.py``) keeps raw ``NamedSharding``/
    ``device_put`` placement out of ``models/`` and ``serve/``.

  * **Device-side resharding.** ``reshard(tree, src, dst, mesh)``
    lowers a src→dst layout change to a device-side collective
    program in the spirit of "Memory-efficient array redistribution
    through portable collective communication" (arXiv:2112.01075):
    the (src, dst) spec pair is classified into the collective class
    it requires (all-gather / slice / all-to-all / gather+slice
    decomposition), the wire bytes are accounted per the comms
    layer's ring model (``CommSync.stats`` convention), and the
    transfer itself runs as one compiled identity program with
    ``out_shardings`` — the XLA partitioner emits exactly those
    collectives, ON DEVICE. The host gather + re-put round trip this
    replaces (``np.asarray`` every leaf, ``device_put`` it back —
    what checkpoint-restore placement, SSP resume-renegotiation and
    ``tda serve`` artifact load all paid) moves ``2·B`` bytes per
    leaf over PCIe and serializes on the host; the device program
    moves only the accounted wire bytes over the interconnect.
    ``reshard.*`` telemetry counters feed a ``tda report`` line.

Rule-table grammar::

    RuleTable("als_train", (
        (r"^R$", P(DATA_AXIS, None)),   # ratings: row-sharded
        (r"^U$", P(DATA_AXIS, None)),   # user factors: row-sharded
        (r"^V$", P(MODEL_AXIS, None)),  # item factors: model axis
    ))

Leaves are named by their pytree path (dict keys / dataclass fields /
sequence indices, ``/``-joined — Optax-style nested state matches with
rules like ``r"inner/.*/mu$"``); the FIRST matching rule wins; scalars
(0-d or size-1 leaves) replicate without consulting the table.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any

import numpy as np

from tpu_distalg.parallel.mesh import DATA_AXIS, MODEL_AXIS


class PartitionRuleError(ValueError):
    """A leaf no rule matches, an unknown table name, or a reshard
    between tables that do not cover the same leaves."""


def _spec_tuple(spec) -> tuple:
    """PartitionSpec → a comparable tuple (PartitionSpec equality is
    fine, but a canonical tuple also strips trailing Nones so
    ``P('data')`` and ``P('data', None)`` compare equal on the same
    array rank — they place identically)."""
    t = tuple(spec)
    while t and t[-1] is None:
        t = t[:-1]
    return t


def specs_equal(a, b) -> bool:
    return _spec_tuple(a) == _spec_tuple(b)


@dataclasses.dataclass(frozen=True)
class RuleTable:
    """An ordered ``(regex, PartitionSpec)`` rule list naming one
    model's placement. ``spec_for`` is the whole matching contract:
    scalars replicate, first ``re.search`` match wins, no match is a
    hard :class:`PartitionRuleError`."""

    name: str
    rules: tuple  # ((pattern_str, PartitionSpec), ...)

    def spec_for(self, leaf_name: str, shape: tuple):
        from jax.sharding import PartitionSpec as P

        if len(shape) == 0 or int(np.prod(shape)) == 1:
            return P()  # never partition scalar values
        for pat, spec in self.rules:
            if re.search(pat, leaf_name) is not None:
                return spec
        raise PartitionRuleError(
            f"no partition rule in table {self.name!r} matches leaf "
            f"{leaf_name!r} (shape {tuple(shape)}) — every non-scalar "
            f"leaf must be named by a rule; add one to the table in "
            f"parallel/partition.py (rules: "
            f"{[p for p, _ in self.rules]})")


# --------------------------------------------------------------- registry

_REGISTRY: dict[str, RuleTable] = {}


def register(table: RuleTable, *, replace: bool = False) -> RuleTable:
    if not replace and table.name in _REGISTRY:
        raise PartitionRuleError(
            f"rule table {table.name!r} is already registered")
    _REGISTRY[table.name] = table
    return table


def table(name: str | RuleTable) -> RuleTable:
    if isinstance(name, RuleTable):
        return name
    try:
        return _REGISTRY[name]
    except KeyError:
        raise PartitionRuleError(
            f"unknown rule table {name!r} (registered: "
            f"{sorted(_REGISTRY)})") from None


def registered() -> tuple[str, ...]:
    return tuple(sorted(_REGISTRY))


# ----------------------------------------------------- row ownership


def row_bounds(n_rows: int, n_shards: int) -> np.ndarray:
    """The ``(n_shards + 1,)`` int64 cut points of ``np.array_split``'s
    contract over ``n_rows`` leading-dim rows: the first ``n_rows %
    n_shards`` shards own ``n_rows // n_shards + 1`` rows, the rest
    ``n_rows // n_shards`` — uneven splits are first-class (a shard
    count that does not divide the model axis is the NORMAL case).
    Shard ``i`` owns ``[bounds[i], bounds[i + 1])``."""
    if n_shards < 1:
        raise PartitionRuleError(
            f"n_shards must be >= 1, got {n_shards}")
    base, extra = divmod(int(n_rows), int(n_shards))
    sizes = np.full((int(n_shards),), base, np.int64)
    sizes[:extra] += 1
    return np.concatenate(
        [np.zeros((1,), np.int64), np.cumsum(sizes, dtype=np.int64)])


@dataclasses.dataclass(frozen=True)
class LeafOwnership:
    """One leaf's placement across row shards: either row-partitioned
    (``bounds`` holds the cut points) or whole on shard ``owner``."""

    name: str
    shape: tuple
    sharded: bool
    bounds: np.ndarray | None = None
    owner: int = 0

    def range_of(self, shard: int) -> tuple[int, int]:
        """The ``[lo, hi)`` leading-dim row range ``shard`` owns (an
        empty range for a non-owner of a whole leaf)."""
        if self.sharded:
            return int(self.bounds[shard]), int(self.bounds[shard + 1])
        n = int(self.shape[0]) if len(self.shape) else 1
        return (0, n) if shard == self.owner else (0, 0)

    def owner_of(self, rows: np.ndarray) -> np.ndarray:
        """Per-row owning shard ids (int64), vectorized."""
        rows = np.asarray(rows, np.int64)
        if not self.sharded:
            return np.full(rows.shape, self.owner, np.int64)
        return np.searchsorted(self.bounds, rows, side="right") - 1


class RowOwnershipMap:
    """The partition-table-driven row-ownership map — ONE derivation of
    "which shard owns which leading-dim rows of which leaf", shared by
    the PS tier's center sharding (``cluster/ps.split_center``), the
    sharded row store (``cluster/rowstore.py``), and the cluster graph/
    ALS engines that partition their work by it. A leaf whose spec in
    the model's rule table shards ANY dim row-splits on axis 0 with
    ``np.array_split`` arithmetic (:func:`row_bounds` — the historical
    ``ps.split_center`` slicing, now first-class); a replicated-spec or
    scalar leaf lives whole on shard 0. Derived from the SAME
    :class:`RuleTable` that drives the device-side ``shardings()`` —
    one table per model names both placements."""

    def __init__(self, shapes: dict, table_name, n_shards: int):
        if n_shards < 1:
            raise PartitionRuleError(
                f"n_shards must be >= 1, got {n_shards}")
        tbl = table(table_name)
        self.table_name = tbl.name
        self.n_shards = int(n_shards)
        self.leaves: dict[str, LeafOwnership] = {}
        for name, shape in shapes.items():
            shape = tuple(int(d) for d in shape)
            spec = tbl.spec_for(name, shape)
            sharded = any(e is not None for e in tuple(spec))
            if sharded and len(shape) >= 1 and shape[0] >= 1:
                self.leaves[name] = LeafOwnership(
                    name, shape, True,
                    bounds=row_bounds(shape[0], self.n_shards))
            else:
                self.leaves[name] = LeafOwnership(
                    name, shape, False, owner=0)

    @classmethod
    def for_center(cls, center: dict, table_name,
                   n_shards: int) -> "RowOwnershipMap":
        return cls({k: np.asarray(v).shape for k, v in center.items()},
                   table_name, n_shards)

    def __getitem__(self, name: str) -> LeafOwnership:
        try:
            return self.leaves[name]
        except KeyError:
            raise PartitionRuleError(
                f"leaf {name!r} is not in the {self.table_name!r} "
                f"ownership map (known: {sorted(self.leaves)})"
            ) from None

    def split(self, center: dict) -> list[dict]:
        """Per-shard sub-dicts of ``center`` (row slices copied) — the
        exact byte-level output of the historical
        ``ps.split_center``."""
        shards: list[dict] = [{} for _ in range(self.n_shards)]
        for name, leaf in center.items():
            leaf = np.asarray(leaf)
            own = self[name]
            if own.sharded:
                for i in range(self.n_shards):
                    lo, hi = own.range_of(i)
                    shards[i][name] = leaf[lo:hi].copy()
            else:
                shards[own.owner][name] = leaf.copy()
        return shards

    def join(self, shards: list[dict]) -> dict:
        """Inverse of :meth:`split` — concatenate row slices in shard
        order, pass whole leaves through."""
        out: dict = {}
        for name, own in self.leaves.items():
            pieces = [sh[name] for sh in shards if name in sh]
            if not pieces:
                continue
            out[name] = (pieces[0].copy() if len(pieces) == 1
                         else np.concatenate(pieces, axis=0))
        return out


# ---------------------------------------------------------- leaf naming


def _key_str(k) -> str:
    from jax import tree_util as jtu

    if isinstance(k, jtu.DictKey):
        return str(k.key)
    if isinstance(k, jtu.SequenceKey):
        return str(k.idx)
    if isinstance(k, jtu.GetAttrKey):
        return str(k.name)
    if isinstance(k, jtu.FlattenedIndexKey):
        return str(k.key)
    return str(k)


def named_leaves(tree) -> list[tuple[str, Any]]:
    """``[(path_name, leaf), ...]`` — dict keys / attr names / indices
    joined with ``/`` (the name the rule regexes match)."""
    from jax.tree_util import tree_flatten_with_path

    leaves, _ = tree_flatten_with_path(tree)
    return [("/".join(_key_str(k) for k in path) or "leaf", v)
            for path, v in leaves]


def _tree_map_named(fn, tree):
    """Map ``fn(name, leaf)`` over the tree, preserving structure."""
    import jax
    from jax.tree_util import tree_flatten_with_path

    leaves, treedef = tree_flatten_with_path(tree)
    out = [fn("/".join(_key_str(k) for k in path) or "leaf", v)
           for path, v in leaves]
    return jax.tree.unflatten(treedef, out)


# ----------------------------------------------------- generated fns


def match_partition_rules(tbl, tree):
    """Pytree of ``PartitionSpec`` for ``tree`` under table ``tbl`` —
    the SNIPPETS.md [2] shape; supports Flax/Optax-style nested state
    via the path-joined names."""
    t = table(tbl)
    return _tree_map_named(
        lambda name, leaf: t.spec_for(name, np.shape(leaf)), tree)


def shardings(tbl, tree, mesh):
    """Pytree of ``NamedSharding`` for ``tree`` under ``tbl``."""
    from jax.sharding import NamedSharding

    t = table(tbl)
    return _tree_map_named(
        lambda name, leaf: NamedSharding(
            mesh, t.spec_for(name, np.shape(leaf))), tree)


def leaf_sharding(tbl, leaf_name: str, mesh, *, shape=(2, 2)):
    """The ``NamedSharding`` table ``tbl`` assigns leaf ``leaf_name``
    — for call sites that place one bare array (``shape`` only
    matters for the scalar short-circuit; the default is non-scalar).
    """
    from jax.sharding import NamedSharding

    return NamedSharding(mesh, table(tbl).spec_for(leaf_name, shape))


def _stage(x):
    """A ``device_put``-ready leaf WITHOUT committing it anywhere: a
    device array passes through (device_put reshards it), anything
    else becomes a host ndarray. A ``jnp.asarray`` here would eagerly
    commit the FULL leaf to the default device before the re-layout —
    a whole-array device-0 copy the 'one H2D direct to the final
    layout' contract exists to avoid (device_put canonicalizes dtypes
    the same way, so values land identically)."""
    import jax

    return x if isinstance(x, jax.Array) else np.asarray(x)


def put(x, leaf_name: str, tbl, mesh):
    """Place ONE array per its table rule (host→device or device
    re-layout; ``jax.device_put`` resolves either)."""
    import jax

    return jax.device_put(
        _stage(x), leaf_sharding(tbl, leaf_name, mesh,
                                 shape=np.shape(x)))


def place(tree, tbl, mesh):
    """Place every leaf of ``tree`` per its table rule. Host leaves
    take one H2D directly to their FINAL layout (each device receives
    only its shard) — the checkpoint-restore-placement seam."""
    import jax

    shs = shardings(tbl, tree, mesh)
    return jax.tree.map(
        lambda x, s: jax.device_put(_stage(x), s), tree, shs)


def constrain(x, leaf_name: str, tbl, mesh):
    """``lax.with_sharding_constraint`` per the table rule — the
    inside-jit spelling of :func:`put`."""
    from jax import lax

    return lax.with_sharding_constraint(
        x, leaf_sharding(tbl, leaf_name, mesh, shape=np.shape(x)))


def gather(tree):
    """Host copies of every leaf (the np.asarray gather the device
    reshard path exists to avoid — kept for checkpoint WRITES, which
    are host-bound by nature, and as the A/B baseline)."""
    import jax

    return jax.tree.map(lambda x: np.asarray(x), tree)


def ensure(tree, tbl, mesh):
    """Idempotent placement — the hot-seam helper. Per leaf:

      * already a committed device array in the table's layout → passed
        through untouched (zero copies);
      * a device array in ANOTHER layout → device-side re-layout
        (``device_put`` to the target sharding — no host round trip);
      * a host array (a restored checkpoint leaf) → one H2D direct to
        the final layout.

    Replaces the ``np.asarray(x)`` + ``device_put`` round trip the
    segmented runners used to pay EVERY segment on state that was
    already resident and correctly placed."""
    import jax

    shs = shardings(tbl, tree, mesh)

    def one(x, s):
        if isinstance(x, jax.Array) and getattr(x, "sharding", None) \
                is not None and x.sharding == s:
            return x
        return jax.device_put(_stage(x), s)

    return jax.tree.map(one, tree, shs)


# ------------------------------------------------------------- reshard


def _spec_dim_degrees(spec, mesh) -> list[int]:
    """Per-dimension shard degree the spec imposes (1 = that dim is
    not cut)."""
    out = []
    for entry in tuple(spec):
        if entry is None:
            out.append(1)
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        n = 1
        for ax in axes:
            n *= int(mesh.shape[ax])
        out.append(n)
    return out


def pad_amounts(shape, spec, mesh) -> tuple[int, ...]:
    """Per-dimension tail padding that makes ``shape`` divisible by
    the spec's shard degrees — all zeros when the layout is already
    even (the historical fast path). The uneven case is exactly what
    an elastic cluster shrinking to a worker count that does not
    divide the model axis produces; the padding is inert zeros, the
    ALS model-axis convention."""
    degs = _spec_dim_degrees(spec, mesh)
    return tuple(
        ((-int(dim)) % degs[i]) if i < len(degs) and degs[i] > 1
        else 0
        for i, dim in enumerate(shape))


def spec_shards(spec, mesh) -> int:
    """Number of distinct shards the spec cuts the array into on this
    mesh (product of the named axes' sizes; 1 == replicated)."""
    n = 1
    for entry in tuple(spec):
        if entry is None:
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        for ax in axes:
            n *= int(mesh.shape[ax])
    return n


def _canonical_spec(spec, mesh) -> tuple:
    """The spec with size-1 mesh axes dropped — ``P('data','model')``
    on a 4×1 mesh PLACES identically to ``P('data')``, so the plan
    must classify the pair as a no-op, not an all-to-all (review-
    caught: spelling-only differences were accounted as real
    collectives with nonzero wire bytes on model=1 meshes)."""
    out = []
    for entry in tuple(spec):
        if entry is None:
            out.append(None)
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        axes = tuple(a for a in axes if int(mesh.shape[a]) > 1)
        out.append(None if not axes
                   else (axes if len(axes) > 1 else axes[0]))
    while out and out[-1] is None:
        out.pop()
    return tuple(out)


def _leaf_plan(shape, dtype, src_spec, dst_spec, mesh,
               true_shape=None) -> dict:
    """Classify ONE leaf's src→dst transition into the collective
    class the pair requires and account its per-shard wire bytes
    under the comms layer's ring model (``CommSync.stats``):

      ==============  =======================  ======================
      transition      collective               bytes_wire (per shard)
      ==============  =======================  ======================
      same spec       none                     0
      repl → shard    local slice              0
      shard → repl    ring all-gather          ``B·(n_s−1)/n_s``
      shard → shard,  all-to-all               ``(B/n_s)·(n_s−1)/n_s``
      equal degree
      shard → shard,  all-gather + slice       ``B·(n_s−1)/n_s``
      degree change   (decomposition)
      ==============  =======================  ======================

    ``B`` = the leaf's full byte size. The decomposed degree-change
    row is an upper bound (arXiv:2112.01075 §4 shows tighter programs
    exist for some factorizations); the program actually emitted is
    the XLA partitioner's lowering of the (src, dst) sharding pair —
    always device-side. ``bytes_host_roundtrip`` is what the gather +
    re-put alternative moves over PCIe (full D2H + full H2D).

    UNEVEN dst layouts (a sharded dim the dst degree does not divide)
    go pad-reshard-slice: the leaf is zero-padded up to divisibility
    INSIDE the compiled program, moves at the padded size — which is
    what ``bytes_wire``/``bytes_logical`` account, with the overhead
    itemized as ``bytes_padding`` and the per-dim amounts as ``pad``
    — and a later reshard back (``true_shapes``) slices the padding
    off again. ``true_shape`` (when given) is the logical shape a
    previously-padded input is first sliced back to."""
    true = tuple(true_shape) if true_shape is not None else tuple(shape)
    pads = pad_amounts(true, dst_spec, mesh)
    moved = tuple(t + p for t, p in zip(true, pads))
    itemsize = np.dtype(dtype).itemsize
    nbytes = int(np.prod(moved)) if moved else 1
    nbytes = int(nbytes * itemsize)
    true_bytes = int((int(np.prod(true)) if true else 1) * itemsize)
    n_s = spec_shards(src_spec, mesh)
    n_d = spec_shards(dst_spec, mesh)
    reshaped = tuple(true) != tuple(shape) or any(pads)
    if not reshaped and _canonical_spec(src_spec, mesh) == \
            _canonical_spec(dst_spec, mesh):
        op, wire = "noop", 0.0
    elif n_s == 1:
        op, wire = "slice", 0.0
    elif n_d == 1:
        op, wire = "all_gather", nbytes * (n_s - 1) / n_s
    elif n_s == n_d:
        op, wire = "all_to_all", (nbytes / n_s) * (n_s - 1) / n_s
    else:
        op, wire = "gather_slice", nbytes * (n_s - 1) / n_s
    plan = {"op": op, "bytes_wire": int(round(wire)),
            "bytes_logical": nbytes,
            "bytes_host_roundtrip": 0 if op == "noop" else 2 * nbytes}
    if any(pads):
        plan["pad"] = pads
        plan["bytes_padding"] = nbytes - true_bytes
        plan["padded_shape"] = moved
    if tuple(true) != tuple(shape):
        plan["true_shape"] = tuple(true)
    return plan


def reshard_stats(tree, src_tbl, dst_tbl, mesh, *,
                  true_shapes: dict | None = None) -> dict:
    """The whole tree's reshard plan + byte accounting (host-side,
    static — no device work): per-leaf plans plus totals, including
    ``bytes_padding`` — the inert-zero overhead uneven dst layouts
    pay for divisibility (pad-reshard-slice). ``true_shapes`` maps
    leaf name → pre-pad logical shape for inputs a PREVIOUS uneven
    reshard padded (the slice half of the round trip). Raises
    :class:`PartitionRuleError` when either table fails to name a
    leaf (the tables must COVER the tree to reshard it)."""
    src_t, dst_t = table(src_tbl), table(dst_tbl)
    leaves: dict[str, dict] = {}
    tot_wire = tot_logical = tot_host = tot_pad = n_moved = 0
    for name, leaf in named_leaves(tree):
        shape = np.shape(leaf)
        dtype = getattr(leaf, "dtype", np.float32)
        plan = _leaf_plan(
            shape, dtype,
            src_t.spec_for(name, shape),
            dst_t.spec_for(name, shape), mesh,
            true_shape=(true_shapes or {}).get(name))
        leaves[name] = plan
        tot_wire += plan["bytes_wire"]
        tot_logical += plan["bytes_logical"]
        tot_host += plan["bytes_host_roundtrip"]
        tot_pad += plan.get("bytes_padding", 0)
        n_moved += plan["op"] != "noop"
    return {"leaves": leaves, "bytes_wire": tot_wire,
            "bytes_logical": tot_logical,
            "bytes_host_roundtrip": tot_host,
            "bytes_padding": tot_pad,
            "n_leaves": len(leaves), "n_moved": n_moved,
            "src": src_t.name, "dst": dst_t.name}


def row_block_stats(n_rows: int, block_rows: int, *,
                    n_shards: int = 1, row_bytes: int = 4) -> dict:
    """Out-of-core row-block accounting (pure arithmetic, no mesh):
    how many gathered blocks a ``block_rows`` granularity yields per
    shard, the pad rows divisibility costs, and the per-block wire
    bytes. The autotuner's block-rows chooser joins this against the
    measured copy bandwidth; it is the block-granularity sibling of
    :func:`reshard_stats`'s ``bytes_padding`` accounting."""
    n_rows = max(1, int(n_rows))
    block_rows = max(1, int(block_rows))
    n_shards = max(1, int(n_shards))
    per_shard = -(-n_rows // n_shards)             # ceil
    n_blocks = -(-per_shard // block_rows)
    padded = n_blocks * block_rows * n_shards
    pad_rows = padded - n_rows
    return {"n_blocks": int(n_blocks),
            "rows_per_shard": int(per_shard),
            "padded_rows": int(padded),
            "pad_rows": int(pad_rows),
            "waste_fraction": float(pad_rows) / float(padded),
            "block_bytes": int(block_rows) * int(row_bytes)}


def reshard(tree, src_tbl, dst_tbl, mesh, *, emit: bool = True,
            true_shapes: dict | None = None):
    """Re-lay ``tree`` out from ``src_tbl``'s placement to
    ``dst_tbl``'s, DEVICE-SIDE: one compiled identity program whose
    ``out_shardings`` are the destination table's — the XLA
    partitioner lowers the (src, dst) pair to the all-gather /
    slice / all-to-all program :func:`reshard_stats` accounts, and no
    device-resident leaf byte touches the host.

    The input's ACTUAL layout is not forced into ``src_tbl`` first —
    the compiled program reshards from whatever sharding each leaf
    carries; ``src_tbl`` declares the layout the plan/accounting
    describes, and at every registered seam the caller's tree IS in
    that layout. A host-resident leaf is handed to the program as a
    host ndarray (no src placement) — for such leaves the
    ``bytes_host_roundtrip``-avoided figure describes the device-
    resident seam this function exists for, not that call. Destination
    dims must divide the dst spec's axis sizes — the tables' own
    padding conventions (ALS model-axis padding, parallelize row
    padding) guarantee that at the registered seams.

    UNEVEN dst layouts are first-class via pad-reshard-slice: a leaf
    whose sharded dim the dst degree does not divide is zero-padded
    to divisibility INSIDE the same compiled program (one launch, no
    extra host trip), lands in dst layout at the padded shape, and
    the padding is itemized in :func:`reshard_stats`
    (``bytes_padding`` / per-leaf ``pad``). Passing ``true_shapes``
    (leaf name → logical shape) on a LATER reshard slices the padding
    off on the way back out — the round trip is bitwise the original
    (pinned by tests). Padded leaves are inert zeros past the true
    extent, the ALS model-axis convention.

    Emits ``reshard.bytes_wire`` / ``bytes_logical`` / ``leaves`` /
    ``syncs`` counters plus a ``reshard`` event (rendered by
    ``tda report``); ``emit=False`` for accounting-free use in inner
    loops that batch their own telemetry."""
    import jax

    st = reshard_stats(tree, src_tbl, dst_tbl, mesh,
                       true_shapes=true_shapes)
    src = jax.tree.map(_stage, tree)
    # destination shardings are computed at the FINAL (possibly
    # padded/sliced) shapes — the scalar short-circuit and the rule
    # match only consult shape via spec_for, which is shape-stable
    # under tail padding for every registered table
    final = _tree_map_named(
        lambda name, leaf: jax.ShapeDtypeStruct(
            tuple(st["leaves"][name].get(
                "padded_shape",
                st["leaves"][name].get("true_shape",
                                       np.shape(leaf)))),
            getattr(leaf, "dtype", np.float32)),
        tree)
    dst_sh = shardings(dst_tbl, final, mesh)
    transforms = tuple(
        (st["leaves"][name].get("true_shape"),
         st["leaves"][name].get("pad"))
        for name, _ in named_leaves(tree))
    out = _reshard_program(dst_sh, transforms)(src)
    if emit:
        emit_reshard_counters(st)
    return out


#: compiled reshard programs keyed by (destination-sharding tree,
#: per-leaf shape transforms) — ``jax.jit`` caches on FUNCTION
#: IDENTITY, so a fresh ``jit(lambda t: t, ...)`` per call would
#: re-trace+compile every reshard (review-caught: ~8 ms/call forever
#: vs ~10 µs cached); the hot seams (serve model builds, bench
#: repeats) hit this cache
_RESHARD_PROGRAMS: dict = {}


def _reshard_program(dst_sh, transforms=None):
    import jax

    leaves, treedef = jax.tree.flatten(dst_sh)
    transforms = transforms or tuple((None, None) for _ in leaves)
    key = (treedef, tuple(leaves), transforms)
    fn = _RESHARD_PROGRAMS.get(key)
    if fn is None:
        def _apply(t):
            import jax.numpy as jnp

            flat, td = jax.tree.flatten(t)
            out = []
            for x, (true_shape, pads) in zip(flat, transforms):
                # slice first (a previously-padded input's tail zeros
                # come off), then pad for the dst degrees — both fuse
                # into the ONE compiled relayout program
                if true_shape is not None and \
                        tuple(x.shape) != tuple(true_shape):
                    x = x[tuple(slice(0, s) for s in true_shape)]
                if pads is not None and any(pads):
                    x = jnp.pad(x, [(0, int(p)) for p in pads])
                out.append(x)
            return jax.tree.unflatten(td, out)

        fn = _RESHARD_PROGRAMS[key] = jax.jit(
            _apply, out_shardings=dst_sh)
    return fn


def host_gather_reshard(tree, dst_tbl, mesh,
                        true_shapes: dict | None = None):
    """The A/B baseline :func:`reshard` replaces: gather every leaf to
    THIS host (full D2H), then ``device_put`` back in the destination
    layout (full H2D) — ``2·B`` PCIe bytes per leaf and a host-RAM
    copy of the whole tree. Bitwise-identical output (both paths move
    the same values, including the uneven-layout pad/slice, applied
    here on host; tests pin it); kept for the bench A/B and as the
    fallback spelling on meshes the compiled path cannot address."""
    dst_t = table(dst_tbl)
    host = gather(tree)

    def one(name, x):
        true = (true_shapes or {}).get(name)
        if true is not None and tuple(x.shape) != tuple(true):
            x = x[tuple(slice(0, s) for s in true)]
        pads = pad_amounts(np.shape(x),
                           dst_t.spec_for(name, np.shape(x)), mesh)
        if any(pads):
            x = np.pad(x, [(0, int(p)) for p in pads])
        return x

    return place(_tree_map_named(one, host), dst_tbl, mesh)


def emit_reshard_counters(st: dict) -> dict:
    """Bump the ``reshard.*`` telemetry counters for one reshard and
    record the event (no-op when telemetry is disabled)."""
    from tpu_distalg.telemetry import events as tevents

    tevents.counter("reshard.bytes_wire", st["bytes_wire"])
    tevents.counter("reshard.bytes_logical", st["bytes_logical"])
    tevents.counter("reshard.bytes_host_avoided",
                    st["bytes_host_roundtrip"])
    tevents.counter("reshard.leaves", st["n_moved"])
    tevents.counter("reshard.syncs", 1)
    tevents.emit("reshard", src=st["src"], dst=st["dst"],
                 n_leaves=st["n_leaves"], n_moved=st["n_moved"],
                 bytes_wire=st["bytes_wire"])
    return st


# ------------------------------------------------- registered tables
#
# Every model's placement, as data. The leaf names are the ones the
# trainers use for their state/data pytrees; DATA_AXIS/MODEL_AXIS are
# the mesh axes from parallel/mesh.py. P is imported lazily at module
# import (jax.sharding is cheap and jax is a hard dep of this package).

from jax.sharding import PartitionSpec as _P  # noqa: E402

#: LR / plain SSGD / the SGD family's replicated-center layout:
#: weights and eval data replicated, per-shard state row-sharded.
TABLE_LR = register(RuleTable("lr", (
    (r"^(w|weights|delta)$", _P()),
    (r"^(res|residual)$", _P(DATA_AXIS, None)),
    (r"^(X2?|X_data)$", _P(DATA_AXIS, None)),
    (r"^(y|mask|valid)$", _P(DATA_AXIS)),
    (r"^(X_test|y_test|accs?|acc0?|clocks?|pend|basegen|stale)$",
     _P()),
)))

#: plain SSGD shares LR's layout wholesale (same leaf vocabulary:
#: replicated center w, row-sharded residual/packed data, replicated
#: SSP clock vector) plus the per-shard SSP window carries.
TABLE_SSGD = register(RuleTable("ssgd", (
    (r"^(wl|accd|ws)$", _P(DATA_AXIS, None)),
) + TABLE_LR.rules))

#: the tp split (sampler='fused_gather' + feature_sharded): packed
#: design matrix sharded data × model, augmented weights model-sharded.
TABLE_SSGD_TP = register(RuleTable("ssgd_tp", (
    (r"^(X2?|X_data)$", _P(DATA_AXIS, MODEL_AXIS)),
    (r"^(w|weights)$", _P(MODEL_AXIS)),
    (r"^(res|residual)$", _P(DATA_AXIS, None)),
    (r"^(y|mask|valid)$", _P(DATA_AXIS)),
    (r"^(X_test|y_test|accs?|acc0?)$", _P()),
)))

#: feature-sharded bernoulli SSGD: same 2-D placement as the tp split
#: (the table IS the code path — both spell P(data, model) / P(model)).
TABLE_SSGD_FEATURE_SHARDED = register(
    RuleTable("ssgd_feature_sharded", TABLE_SSGD_TP.rules))

#: the local-update family (local_sgd driving ma/bmuf/easgd): one
#: replicated center + per-replica row-sharded models/residuals.
TABLE_LOCAL_SGD = register(RuleTable("local_sgd", (
    (r"^(ws|res|residual)$", _P(DATA_AXIS, None)),
    (r"^(w|weights|delta)$", _P()),
    (r"^(X2?|X_data)$", _P(DATA_AXIS, None)),
    (r"^(y|mask|valid)$", _P(DATA_AXIS)),
    (r"^(X_test|y_test|accs?|acc0?|clocks?|stale)$", _P()),
)))
for _alias in ("ma", "bmuf", "easgd"):
    register(RuleTable(_alias, TABLE_LOCAL_SGD.rules))

#: k-means: points row-sharded (parallelize), centers replicated.
TABLE_KMEANS = register(RuleTable("kmeans", (
    (r"^(points|X2|m2)$", _P(DATA_AXIS, None)),
    (r"^(mask|valid)$", _P(DATA_AXIS)),
    (r"^(centers|n_seen)$", _P()),
)))

#: ALS training layout: ratings + user factors row-sharded over data,
#: item factors sharded over the MODEL axis (fit() pads n so this
#: always engages; the warned disengage path places V replicated).
#: ``V0`` — V at a sweep/segment ENTRY — is replicated: the engaged
#: layout is applied by constraint INSIDE the compiled sweep, and an
#: entry-sharded V would change the Gram matmul's reduction order
#: (the golden-hash pins hold the refactor to bitwise identity).
TABLE_ALS_TRAIN = register(RuleTable("als_train", (
    (r"^(R|U)$", _P(DATA_AXIS, None)),
    (r"^V0$", _P()),
    (r"^V$", _P(MODEL_AXIS, None)),
)))

#: ALS serving layout (serve/artifacts.py): user factors replicated
#: (any shard may score any user), item factors model-sharded for the
#: fused per-shard top-k. reshard('als_train' → 'als_serve') is the
#: train→serve seam: U all-gathers, V stays put — device-side.
TABLE_ALS_SERVE = register(RuleTable("als_serve", (
    (r"^U$", _P()),
    (r"^V$", _P(MODEL_AXIS, None)),
)))

#: dense transitive closure: the V×V boolean path matrix row-sharded
#: over data (the boolean-matmul fixpoint's only placed operand; the
#: sparse path's pair buffer stays replicated by design — see
#: models/transitive_closure.py).
TABLE_CLOSURE = register(RuleTable("closure_dense", (
    (r"^(paths|edges)$", _P(DATA_AXIS, None)),
)))

#: PageRank: edge/plan arrays contiguously sharded over data, the
#: rank vector and degree tables replicated (the sweep's all-reduce
#: owns rank combination).
TABLE_PAGERANK = register(RuleTable("pagerank", (
    (r"^(src|dst|w_e|emask|gbase|sbase|base)$", _P(DATA_AXIS)),
    (r"^(src_lane|src_row|dst_row|dst_lane|row|lane)$",
     _P(DATA_AXIS, None)),
    (r"^(ranks|inv_deg|has_out)$", _P()),
)))

#: cluster-sharded PageRank: the rank vector ROW-PARTITIONED across
#: the PS tier (the rowstore twin of TABLE_PAGERANK, whose in-process
#: sweep replicates ranks and lets the all-reduce own combination);
#: the static degree tables stay whole on shard 0.
TABLE_PAGERANK_CLUSTER = register(RuleTable("pagerank_cluster", (
    (r"^ranks$", _P(DATA_AXIS)),
    (r"^(deg|inv_deg|has_out)$", _P()),
)))

#: streamed-SSGD eval operands: replicated (pinned to local compute
#: via shard_map in the trainer — see ssgd_stream.py).
TABLE_SSGD_STREAM = register(RuleTable("ssgd_stream", (
    (r"^(X_test|y_test)$", _P()),
) + TABLE_LR.rules))

#: the reshard pairs the system actually exercises (train→serve
#: artifact load; the 2-D ssgd layouts to/from pure-dp) — the
#: equivalence tests iterate this registry, so a new pair added here
#: is automatically held to the reshard ≡ gather+re-put contract.
RESHARD_PAIRS = (
    ("als_train", "als_serve"),
    ("als_serve", "als_train"),
    ("ssgd_feature_sharded", "ssgd"),
    ("ssgd", "ssgd_feature_sharded"),
)
