"""Sharded-array constructors — the RDD/broadcast replacement.

Maps the reference's data-distribution primitives onto ``jax.sharding``:

  * ``parallelize(rows, mesh)``  ≙  ``sc.parallelize(matrix, n_slices).cache()``
    (``/root/reference/optimization/ssgd.py:86``): rows are padded to a
    multiple of the data-axis size and placed as a row-sharded ``jax.Array``
    resident in HBM. A validity mask stands in for the exact partition sizes.
  * ``replicate(tree, mesh)``  ≙  ``sc.broadcast(w)`` (``ssgd.py:95``):
    fully-replicated sharding. Under ``jit`` the compiler keeps replicated
    operands resident on every chip, so the per-iteration re-broadcast of the
    reference costs nothing here.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_distalg.parallel.mesh import DATA_AXIS


def data_sharding(mesh: Mesh, ndim: int = 1) -> NamedSharding:
    """Row-sharded over the data axis; remaining dims replicated."""
    spec = P(DATA_AXIS, *([None] * (ndim - 1)))
    return NamedSharding(mesh, spec)


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def pad_rows(x: np.ndarray | jax.Array, multiple: int):
    """Pad axis 0 up to a multiple; return (padded, valid_mask).

    Spark partitions may be ragged; XLA shards must be equal-sized and
    static. The mask carries the 'true length' through reductions.
    """
    n = x.shape[0]
    n_pad = (-n) % multiple
    mask = np.ones((n + n_pad,), dtype=np.float32)
    if n_pad:
        pad_width = [(0, n_pad)] + [(0, 0)] * (x.ndim - 1)
        x = np.pad(np.asarray(x), pad_width)
        mask[n:] = 0.0
    return x, mask


@dataclasses.dataclass
class ShardedMatrix:
    """A row-sharded dataset: the framework's stand-in for a cached RDD.

    ``data`` is ``(n_padded, ...)`` sharded over the mesh data axis — a
    single array from :func:`parallelize`, possibly a pytree of aligned
    arrays from :func:`build_sharded`; ``mask`` is 1.0 for real rows,
    0.0 for padding; ``n_valid`` is the original row count.
    """

    data: jax.Array
    mask: jax.Array
    n_valid: int

    @property
    def n_padded(self) -> int:
        return self.mask.shape[0]


def parallelize(
    rows: np.ndarray,
    mesh: Mesh,
    *,
    dtype=jnp.float32,
) -> ShardedMatrix:
    """Shard ``rows`` row-wise across the mesh data axis (HBM-resident).

    Equivalent of ``parallelize(matrix, n_slices).cache()`` — but the shard
    placement is declarative (NamedSharding) and permanent; there is no lazy
    lineage to recompute because the array physically lives on the devices.
    """
    n_shards = mesh.shape[DATA_AXIS]
    padded, mask = pad_rows(np.asarray(rows), n_shards)
    sharding = data_sharding(mesh, ndim=padded.ndim)
    data = jax.device_put(jnp.asarray(padded, dtype=dtype), sharding)
    mask_arr = jax.device_put(jnp.asarray(mask), data_sharding(mesh, ndim=1))
    return ShardedMatrix(data=data, mask=mask_arr, n_valid=int(rows.shape[0]))


def replicate(tree, mesh: Mesh):
    """Place every leaf fully-replicated on the mesh (the broadcast op)."""
    sharding = replicated_sharding(mesh)
    return jax.tree.map(
        lambda x: jax.device_put(jnp.asarray(x), sharding), tree
    )


def build_sharded(
    mesh: Mesh,
    n_rows: int,
    make_rows,
    *,
    row_multiple: int = 1,
) -> ShardedMatrix:
    """Construct a row-sharded dataset ON DEVICE — the scale-out sibling
    of :func:`parallelize`.

    ``parallelize`` materializes the full array on the host first
    (``np.pad`` + ``device_put``) — at the 1B-row north-star scale
    (BASELINE.json) that is ~100s of GB of host RAM for data that is
    synthesized anyway (the reference builds its matrix host-side too,
    ``/root/reference/optimization/ssgd.py:86``, which is exactly the
    pattern that cannot scale). Here each shard's rows are generated
    inside a ``shard_map`` body on the device that owns them: host
    memory use is O(1) in ``n_rows`` and every host in a multi-host mesh
    only ever touches its own addressable shards.

    ``make_rows(row_ids)`` must be jittable: given the shard's global row
    ids ``(n_local,)`` it returns a pytree of ``(n_local, ...)`` row
    blocks (e.g. ``(X_rows, y_rows)``). Content should depend only on
    ``row_ids`` (e.g. fold them into a PRNG key), making the dataset
    topology-independent. Rows are padded to a multiple of
    ``row_multiple × n_shards``; padded rows carry mask 0.
    """
    from jax import lax

    from tpu_distalg.parallel.compat import shard_map

    n_shards = mesh.shape[DATA_AXIS]
    mult = n_shards * row_multiple
    n_padded = -(-n_rows // mult) * mult
    n_local = n_padded // n_shards

    def body():
        s = lax.axis_index(DATA_AXIS)
        ids = s * n_local + jnp.arange(n_local)
        rows = make_rows(ids)
        mask = (ids < n_rows).astype(jnp.float32)
        return rows, mask

    # trace abstractly to learn each row block's rank for out_specs
    shapes = jax.eval_shape(
        make_rows, jax.ShapeDtypeStruct((n_local,), jnp.int32)
    )
    specs = jax.tree.map(
        lambda sh: P(DATA_AXIS, *([None] * (sh.ndim - 1))), shapes
    )
    f = shard_map(
        body, mesh=mesh, in_specs=(), out_specs=(specs, P(DATA_AXIS)),
    )
    shardings = jax.tree.map(lambda sp: NamedSharding(mesh, sp), specs)
    data, mask = jax.jit(f, out_shardings=(
        shardings, data_sharding(mesh, 1)
    ))()
    return ShardedMatrix(data=data, mask=mask, n_valid=n_rows)
