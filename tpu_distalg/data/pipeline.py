"""The prefetch pipeline engine — gather ∥ H2D ∥ compute.

The streamed SSGD trainer proved the shape (``models/ssgd_stream.py``,
PR 1): the host gather of the next-next batch runs on a background
producer thread behind a maxsize-1 queue, the H2D ``device_put`` of the
next batch is dispatched before the current step's compute, and the
steady-state rate is ``max(gather, H2D, compute)`` — not their serial
sum. This module is that machinery extracted for EVERY workload that
consumes a :class:`~tpu_distalg.data.sharded.ShardedDataset`.

Invariants the extraction preserves (they are the bitwise contract):

  * block order and content are identical to the serial path — the
    producer gathers ``ids[0], ids[1], ...`` in order, so a consumer's
    trajectory is unchanged by prefetching;
  * host residency is bounded at two gathered batches beyond the one in
    compute (one staged-ready in the queue + the producer's in-flight
    gather);
  * a producer-side exception is forwarded through the queue and
    re-raised in the consumer; on any exit the producer is halted and
    joined (``Prefetcher`` is a context manager, and
    :func:`stream_staged` is a generator whose ``finally`` closes it —
    iterate under ``contextlib.closing`` when you may exit early);
  * a producer thread that DIES without posting anything (a bug, an
    injected ``faults.InjectedKill``) cannot block the consumer
    forever: :meth:`Prefetcher.get` waits in bounded intervals and
    checks producer liveness between them, raising
    :class:`ProducerDiedError` instead of hanging the run.
"""

from __future__ import annotations

import queue
import threading

import numpy as np

from tpu_distalg import faults
from tpu_distalg.telemetry import events as tevents


class ProducerDiedError(RuntimeError):
    """The prefetch producer thread exited without posting the item (or
    an error) the consumer is waiting on — silent thread death, the one
    failure a plain blocking ``Queue.get`` turns into an eternal hang.
    A plain ``RuntimeError`` so ``run_with_restarts`` retries it."""


class Prefetcher:
    """One-deep background producer: ``produce(i)`` for
    ``i in range(n_items)`` lands in arrival order behind a maxsize-1
    queue; :meth:`get` returns the next item or re-raises the
    producer's exception. Use as a context manager — ``__exit__`` halts
    and joins the thread whatever state the queue is in."""

    # liveness-check cadence for get(): long enough to cost nothing on
    # the healthy path, short enough that a dead producer is a prompt,
    # named error instead of a wedged run
    POLL_SECONDS = 0.1

    def __init__(self, produce, n_items: int,
                 name: str = "tda-data-prefetch"):
        self._produce = produce
        self._n = int(n_items)
        self._halt = threading.Event()
        self._q: queue.Queue = queue.Queue(maxsize=1)
        self._thread = (threading.Thread(
            target=self._run, daemon=True, name=name)
            if self._n else None)

    def _offer(self, item) -> bool:
        while not self._halt.is_set():
            try:
                self._q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def _run(self):
        try:
            for i in range(self._n):
                if not self._offer(self._produce(i)):
                    return
        except faults.InjectedKill:
            # die SILENTLY — no error posted. This is the chaos model
            # of a producer killed mid-flight; the consumer's liveness
            # guard in get() must turn it into ProducerDiedError.
            return
        except BaseException as e:  # noqa: BLE001 — re-raised in get()
            self._offer(e)

    def get(self):
        """Next item, or re-raise the producer's forwarded exception.
        Bounded-interval wait with a producer-liveness check: a dead
        producer raises :class:`ProducerDiedError` instead of blocking
        forever (a HUNG-but-alive producer is still waited on — that is
        the heartbeat watchdog's jurisdiction, not this guard's)."""
        while True:
            try:
                item = self._q.get(timeout=self.POLL_SECONDS)
                break
            except queue.Empty:
                th = self._thread
                if th is None or not th.is_alive():
                    # one last non-blocking drain: the producer may have
                    # posted its final item between our timeout and the
                    # liveness check
                    try:
                        item = self._q.get_nowait()
                        break
                    except queue.Empty:
                        tevents.counter("faults.producer_deaths_detected")
                        what = ("was never started" if th is None
                                else f"{th.name} died")
                        raise ProducerDiedError(
                            f"prefetch producer thread {what} without "
                            f"posting an item or an error; the batch it "
                            f"owed will never arrive — restart the "
                            f"stream (run_with_restarts recovers this)"
                        ) from None
        if isinstance(item, BaseException):
            raise item
        return item

    def __enter__(self):
        if self._thread is not None:
            self._thread.start()
        return self

    def __exit__(self, *exc):
        self._halt.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
        return False


def stream_staged(dataset, ids: np.ndarray):
    """Yield one staged device batch per step of ``ids`` ``(T, S, ns)``.

    Host backends (virtual/streamed): the producer thread gathers
    batch t+2 while batch t+1's ``device_put`` is in flight and the
    consumer computes on batch t — the double-buffered loop
    ``ssgd_stream`` ran inline, now behind a generator (``put`` of the
    NEXT batch is dispatched before the CURRENT batch is yielded to the
    consumer's compute). Resident backend: device-side block takes,
    dispatched one ahead for symmetry.

    Each step updates the liveness mark (``data:stream``); on
    exhaustion one ``data_pipeline`` event records the batch/byte
    totals for ``tda report``.
    """
    n_steps = len(ids)
    if dataset.backend == "resident":
        for i in range(n_steps):
            tevents.mark("data:stream", emit_event=False)
            yield dataset.stage(ids[i])
        return
    total_bytes = 0
    with Prefetcher(lambda i: dataset.gather(ids[i]), n_steps) as pf:
        staged = dataset.put(pf.get()) if n_steps else None
        for i in range(n_steps):
            tevents.mark("data:stream", emit_event=False)
            nxt = dataset.put(pf.get()) if i + 1 < n_steps else None
            total_bytes += int(np.prod(staged.shape)) * dataset.itemsize
            yield staged
            staged = nxt
    tevents.emit("data_pipeline", backend=dataset.backend,
                 steps=n_steps, bytes=total_bytes)


def make_host_block_sampler(seed: int, n_shards: int, n_blocks: int,
                            n_sampled: int):
    """Build ONCE the jitted 'fused_gather' block draw on the host CPU
    backend: threefry is platform-deterministic, so these ids equal the
    ones the resident path draws on device — the property that keeps
    streamed trajectories bitwise-equal to resident ones. Returns
    ``draw(ts) -> (T, n_shards, n_sampled)`` local block ids; the jit
    is cached per distinct segment length (building it per call would
    recompile the sampler inside timed/checkpointed loops)."""
    import jax
    import jax.numpy as jnp

    from tpu_distalg.ops import sampling
    from tpu_distalg.utils import prng

    key = prng.root_key(seed)
    cpu = jax.devices("cpu")[0]
    with jax.default_device(cpu):
        f = jax.jit(jax.vmap(lambda t: sampling.sample_block_ids(
            jax.random.fold_in(key, t), n_shards, n_blocks, n_sampled)))

    def draw(ts: np.ndarray) -> np.ndarray:
        with jax.default_device(cpu):
            return np.asarray(f(jnp.asarray(ts, jnp.int32)))

    return draw
