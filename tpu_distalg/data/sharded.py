"""``ShardedDataset`` — one block-addressable dataset, three placements.

SURVEY §2.2's verdict on the reference is that its real framework is
the RDD itself: ``.cache()`` is a hint, and ANY dataset can spill past
memory. Until this subsystem, that capability lived only inside the
streamed SSGD trainer (``models/ssgd_stream.py``) — k-means and ALS
silently capped at one chip's HBM. ``ShardedDataset`` owns the layer
once, for every workload:

  * the dataset is a logical ``(n_rows, row_width)`` matrix, sharded
    CONTIGUOUSLY over the mesh data axis (shard s owns rows
    ``[s·n_local, (s+1)·n_local)``) and addressed at BLOCK granularity
    (``block_rows`` consecutive rows — whole-block DMA is the shape the
    hardware wants; row-granular random access serializes, see
    ``ops/pallas_kernels.fused_grad_sum_gathered``);
  * three interchangeable backends place the SAME bytes differently:

      ``resident``   a device ``jax.Array`` (row-sharded over HBM) —
                     block gathers run on device;
      ``virtual``    a host-RAM ``np.ndarray`` — block gathers are one
                     fancy-index memcpy + async ``device_put``;
      ``streamed``   a disk ``np.memmap`` (a packed cache,
                     ``data/cache.py``) — same gather path, the OS page
                     cache is the only RAM footprint;

  * :meth:`stage` produces the identical staged device batch
    ``(n_shards, n_sampled·block_rows, row_width)`` whichever backend
    holds the bytes, so a training step jitted over staged batches has
    a BITWISE-identical trajectory across backends (asserted in
    tests/test_data.py — the property that makes ``--data-backend`` a
    placement knob, not an algorithm knob);
  * :meth:`stream` runs the pipeline engine (``data/pipeline.py``):
    one-deep background host-gather prefetch + double-buffered
    ``device_put`` so gather ∥ H2D ∥ compute — the machinery
    ``ssgd_stream`` proved, promoted to the subsystem.

Telemetry: gathers and H2D dispatches are ``data:gather`` /
``data:h2d`` spans with ``data.*`` counters (bytes, batches), so
``tda report`` shows where a streamed run spends its time.
"""

from __future__ import annotations

import numpy as np

from tpu_distalg import faults
from tpu_distalg.telemetry import events as tevents

BACKENDS = ("resident", "virtual", "streamed")


def block_geometry(n_rows: int, block_rows: int, n_shards: int,
                   fraction: float | None = None):
    """The block grid every out-of-core path samples on: rows per shard
    padded up to whole blocks, blocks per shard, and (when ``fraction``
    is given) blocks sampled per shard per step. Shared by the virtual
    sampler (``models/ssgd_virtual``), the stream trainer and the
    minibatch k-means/ALS paths so the grids cannot drift apart.
    Returns ``(rows_per_shard, n_blocks, n_sampled)`` (``n_sampled``
    None when ``fraction`` is)."""
    rows_per_shard = -(-n_rows // (n_shards * block_rows)) * block_rows
    n_blocks = rows_per_shard // block_rows
    n_sampled = (None if fraction is None
                 else max(1, round(fraction * n_blocks)))
    return rows_per_shard, n_blocks, n_sampled


def _infer_backend(storage) -> str:
    if isinstance(storage, np.memmap):
        return "streamed"
    if isinstance(storage, np.ndarray):
        return "virtual"
    return "resident"  # a jax.Array (checked in __init__)


class ShardedDataset:
    """See the module docstring. ``storage`` is the ``(n2, pd)`` row
    matrix (device array, host array, or memmap); ``block_rows`` is the
    gather granularity in STORAGE rows (for pack>1 layouts that is
    packed rows — ``gather_block_rows // pack``); ``meta`` carries the
    layout geometry (e.g. the packed-kernel dict) for consumers."""

    def __init__(self, storage, mesh, *, block_rows: int,
                 meta: dict | None = None, backend: str | None = None):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from tpu_distalg.parallel import DATA_AXIS, data_parallel

        self.backend = backend or _infer_backend(storage)
        if self.backend not in BACKENDS:
            raise ValueError(
                f"unknown data backend {self.backend!r}; choose from "
                f"{BACKENDS}")
        n2, pd = storage.shape
        n_shards = mesh.shape[DATA_AXIS]
        if n2 % n_shards:
            raise ValueError(
                f"{n2} storage rows not divisible by {n_shards} shards")
        n2_local = n2 // n_shards
        if block_rows <= 0 or n2_local % block_rows:
            raise ValueError(
                f"per-shard rows {n2_local} not divisible by "
                f"block_rows={block_rows}")
        self.storage = storage
        self.mesh = mesh
        self.meta = dict(meta) if meta else {}
        self.block_rows = int(block_rows)
        self.n_shards = int(n_shards)
        self.n2 = int(n2)
        self.pd = int(pd)
        self.n2_local = int(n2_local)
        self.n_blocks = int(n2_local // block_rows)
        self.itemsize = int(np.dtype(storage.dtype).itemsize)
        self.shard_spec = NamedSharding(mesh, P(DATA_AXIS, None, None))
        self._row_offsets = np.arange(n_shards)[:, None] * n2_local
        # full-array reduction, PER SHARD (axes 1,2 only): the touch
        # runs concurrently with the consumer's previous step, and two
        # in-flight collective programs can deadlock a rendezvous on
        # backends that may start them out of order (seen on the CPU
        # mesh) — so the touch must contain NO cross-device collective.
        self._touch = jax.jit(
            lambda a: jnp.sum(a.astype(jnp.float32), axis=(1, 2)))
        if self.backend == "resident":
            if isinstance(storage, np.ndarray):
                raise ValueError(
                    "resident backend needs a device array — build one "
                    "with ShardedDataset.from_array(backend='resident')")
            bp = self.block_rows

            def _take(Xl, ids_l):
                rows = (ids_l[0][:, None] * bp
                        + jnp.arange(bp)[None, :]).reshape(-1)
                return Xl[rows][None]

            self._device_take = jax.jit(data_parallel(
                _take, mesh,
                in_specs=(P(DATA_AXIS, None), P(DATA_AXIS, None)),
                out_specs=P(DATA_AXIS, None, None)))
        else:
            self._device_take = None
        # CPU-mesh emulation on few host cores starves the rendezvous
        # when several multi-device programs are in flight — consumers
        # (trainers) read this to serialize steps there.
        self.on_tpu = next(iter(mesh.devices.flat)).platform == "tpu"

    # ---- constructors ------------------------------------------------

    @classmethod
    def from_array(cls, array, mesh, *, block_rows: int,
                   meta: dict | None = None, backend: str = "virtual"):
        """Wrap an in-memory ``(n2, pd)`` matrix. ``backend='virtual'``
        keeps it in host RAM; ``backend='resident'`` places it
        row-sharded in device memory (the same bytes — staged batches
        stay bitwise-equal across the two)."""
        if backend == "resident":
            import jax
            import jax.numpy as jnp
            from jax.sharding import NamedSharding, PartitionSpec as P

            from tpu_distalg.parallel import DATA_AXIS

            sharding = NamedSharding(mesh, P(DATA_AXIS, None))
            dev = jax.device_put(jnp.asarray(array), sharding)
            return cls(dev, mesh, block_rows=block_rows, meta=meta,
                       backend="resident")
        if backend == "streamed":
            raise ValueError(
                "backend='streamed' opens a disk cache — use "
                "ShardedDataset.from_cache")
        return cls(np.asarray(array), mesh, block_rows=block_rows,
                   meta=meta, backend=backend)

    @classmethod
    def from_cache(cls, path: str, mesh, *, block_rows: int,
                   layout: str | None = None,
                   expect_geom: dict | None = None):
        """Open a complete packed cache (``data/cache.py``) as the
        streamed backend; header/layout/geometry are validated."""
        from tpu_distalg.data import cache as dcache

        mm, header = dcache.open_cache(path, layout=layout,
                                       expect_geom=expect_geom)
        return cls(mm, mesh, block_rows=block_rows,
                   meta=dict(header.get("geom") or {}),
                   backend="streamed")

    # ---- the gather/stage/stream surface -----------------------------

    def h2d_bytes_per_step(self, n_sampled: int) -> int:
        """Bytes one staged batch moves host→device (0 for resident —
        the gather is an HBM-to-HBM copy, so an H2D roofline over it
        would be bogus)."""
        if self.backend == "resident":
            return 0
        return int(self.n_shards * n_sampled * self.block_rows
                   * self.pd * self.itemsize)

    def gather(self, ids_step: np.ndarray) -> np.ndarray:
        """The HOST side of staging one step: the fancy-index gather of
        the sampled blocks out of the (possibly disk-memmap) matrix —
        for a >RAM dataset this is the dominant per-step cost, which is
        why :meth:`stream` runs it on the prefetch thread. Pure numpy:
        safe off the JAX dispatch thread. ``ids_step`` is
        ``(n_shards, n_sampled)`` LOCAL block ids; returns
        ``(n_shards, n_sampled·block_rows, pd)``."""
        if self.backend == "resident":
            raise ValueError("resident datasets gather on device — "
                             "use stage()")
        bp = self.block_rows
        with tevents.span("data:gather", backend=self.backend):
            # chaos seam: on the streamed path this runs on the
            # prefetch producer thread, so an injected kill here dies
            # silently and exercises the consumer's liveness guard;
            # corrupt (no payload) models checksum-detected bad reads
            faults.inject("data:gather")
            rows = (ids_step[:, :, None] * bp
                    + np.arange(bp)[None, None, :]).reshape(
                        self.n_shards, -1)
            rows = rows + self._row_offsets
            out = self.storage[rows]
        tevents.counter("data.gather_batches")
        tevents.counter("data.gather_bytes", int(out.nbytes))
        return out

    def put(self, gathered: np.ndarray):
        """The DEVICE side: async H2D of one gathered batch onto the
        mesh, TOUCHED with a tiny async per-shard reduction so the
        transfer actually starts now — on tunneled/lazy backends
        ``device_put`` (and even ``block_until_ready`` on its result)
        can defer the copy until first use, which would serialize the
        H2D behind the next step instead of overlapping it."""
        import jax

        with tevents.span("data:h2d", backend=self.backend,
                          bytes=int(gathered.nbytes)):
            faults.inject("data:h2d")
            staged = jax.device_put(gathered, self.shard_spec)
            self._touch(staged)  # async; result dropped
        tevents.counter("data.h2d_batches")
        tevents.counter("data.h2d_bytes", int(gathered.nbytes))
        return staged

    def stage(self, ids_step: np.ndarray):
        """One step's staged batch, any backend: serial gather+put for
        host storage (the shape bench.py's H2D-roofline probe measures
        on purpose — no prefetch), a device-side block take for
        resident storage. Bytes are identical across backends."""
        if self.backend == "resident":
            import jax.numpy as jnp

            return self._device_take(
                self.storage, jnp.asarray(ids_step, jnp.int32))
        return self.put(self.gather(ids_step))

    def stream(self, ids: np.ndarray):
        """Staged batches for every step of ``ids`` ``(T, S, ns)``, in
        order, through the pipeline engine: host backends get the
        prefetch thread + double-buffered puts (gather(t+2) ∥ H2D(t+1)
        ∥ compute(t)); resident storage stages directly (device gathers
        are already async). Use ``contextlib.closing`` (or iterate to
        exhaustion) so an early exit stops the producer thread."""
        from tpu_distalg.data import pipeline

        return pipeline.stream_staged(self, ids)
