"""The packed-cache format — versioned on-disk datasets, atomic publish.

This is the storage half of the out-of-core dataset subsystem: a cache
is a pair ``<path>.bin`` (a flat row-major memmap) + ``<path>.meta.json``
(the header), optionally with named aux payloads (held-out splits,
teacher weights). The format generalizes what
``utils/datasets.streamed_packed_cache`` proved for the streamed SSGD
trainer so EVERY workload (k-means points, ALS rating blocks, packed
SSGD rows) shares one publish/validate/reopen engine instead of
re-growing it per trainer.

Header (``meta.json``) — one JSON object::

    {"format": "tda-packed-cache", "version": 2,
     "layout": "<layout name>",        # what the rows mean
     "dtype": "<numpy/ml_dtypes name>",
     "shape": [n_rows, row_width],
     "geom": {...}}                    # layout-specific geometry

``geom`` carries whatever the producing layout needs to validate a
reopen (shard count, block size, generator seed, ...) — byte-for-byte
equality against the expected geometry is the reopen contract. Caches
written before the subsystem existed (PR 1's ``streamed_packed_cache``)
have a FLAT geometry dict as their whole meta.json; :func:`open_cache`
accepts those through ``legacy_geom`` so a rig's multi-GB cache is not
regenerated over a header format change.

Publish protocol (crash/concurrency-safe, lifted from
``streamed_packed_cache`` and now the single implementation):

  * every artifact is written under a PID/uuid tmp name and
    ``os.replace``d into place — two processes pointed at the same path
    generate independently and the LAST rename wins; content must be
    deterministic in the header, so either winner is byte-identical;
  * publish order is aux files → ``.bin`` → ``meta.json`` LAST: the
    header's presence means "everything before it is complete", so
    readers never see a partial cache whatever instant a crash hits;
  * stale tmp orphans (a ``kill -9`` mid-generation) are swept on the
    next build attempt, age-gated so a CONCURRENT live generator's tmp
    files are never yanked out from under it.

This module imports only numpy/stdlib (telemetry is stdlib-only too):
cache builds run in plain host processes — tests exercise the
two-writer race with real subprocesses.
"""

from __future__ import annotations

import glob
import json
import os
import time
import uuid

import numpy as np

from tpu_distalg import faults
from tpu_distalg.telemetry import events as tevents

# transient-disk-fault retry schedule for a build attempt (the
# ``cache:write`` injection point fires inside each attempt); a real
# outage longer than this is the caller's run_with_restarts' job
BUILD_RETRIES = 2
BUILD_BACKOFF_SECONDS = 0.05

FORMAT = "tda-packed-cache"
FORMAT_VERSION = 2
# a 32 GB generation measures ~15 min on the bench rig; anything this
# old is a crashed generator's orphan, not a live build
STALE_TMP_SECONDS = 6 * 3600.0


def bin_path(path: str) -> str:
    return path + ".bin"


def meta_path(path: str) -> str:
    return path + ".meta.json"


def aux_path(path: str, name: str) -> str:
    return f"{path}.{name}"


def make_header(*, layout: str, dtype, shape, geom: dict) -> dict:
    return {
        "format": FORMAT,
        "version": FORMAT_VERSION,
        "layout": str(layout),
        "dtype": _dtype_name(dtype),
        "shape": [int(x) for x in shape],
        "geom": dict(geom),
    }


def _dtype_name(dtype) -> str:
    return str(np.dtype(dtype)) if not isinstance(dtype, str) else dtype


def resolve_dtype(name: str) -> np.dtype:
    """``np.dtype`` from a header name, including the ml_dtypes names
    (``bfloat16``...) numpy alone does not know."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # a jax dependency — always present here

        return np.dtype(getattr(ml_dtypes, name))


def exists(path: str) -> bool:
    """True iff the cache is COMPLETE (header published after the bin)."""
    return os.path.exists(meta_path(path)) and os.path.exists(bin_path(path))


def read_header(path: str) -> dict | None:
    if not os.path.exists(meta_path(path)):
        return None
    with open(meta_path(path)) as f:
        return json.load(f)


def open_cache(path: str, *, layout: str | None = None,
               expect_geom: dict | None = None,
               legacy_geom: dict | None = None):
    """Reopen a COMPLETE cache read-only: ``(memmap, header)``.

    Raises ``FileNotFoundError`` when the cache is absent/partial and
    ``ValueError`` on any header mismatch — wrong format marker, a
    version this reader does not speak, a different layout, or geometry
    that differs from ``expect_geom`` (the caller's generation
    parameters: reopening a cache built with other ones would silently
    train on the wrong bytes).

    ``legacy_geom``: pre-subsystem caches (PR 1) wrote the flat geometry
    dict as their entire meta.json; when it equals ``legacy_geom`` the
    cache is accepted and wrapped in a synthetic v1 header (``dtype``/
    ``shape`` taken from ``legacy_geom``'s producer via ``expect_geom``
    is not possible, so callers supply them through the returned
    header's ``geom`` as before).
    """
    header = read_header(path)
    if header is None or not os.path.exists(bin_path(path)):
        raise FileNotFoundError(
            f"no complete packed cache at {path!r} (meta.json is "
            "published last — a .bin without it is a half-finished "
            "build)")
    if "format" not in header:
        # legacy flat-geometry meta (pre-versioned caches)
        if legacy_geom is None or header != legacy_geom:
            raise ValueError(
                f"cache at {path} has a legacy header {header} that "
                f"does not match the expected geometry "
                f"{legacy_geom}; delete it or use another path")
        header = {"format": FORMAT, "version": 1, "layout": layout or "",
                  "dtype": None, "shape": None, "geom": dict(legacy_geom)}
        mm = None  # legacy caller opens the memmap itself (knows dtype)
        return mm, header
    if header.get("format") != FORMAT:
        raise ValueError(
            f"cache at {path} is not a {FORMAT} artifact "
            f"(format={header.get('format')!r})")
    if header.get("version") != FORMAT_VERSION:
        raise ValueError(
            f"cache at {path} has format version "
            f"{header.get('version')!r}; this reader speaks "
            f"{FORMAT_VERSION} — regenerate the cache (or upgrade)")
    if layout is not None and header.get("layout") != layout:
        raise ValueError(
            f"cache at {path} holds layout {header.get('layout')!r}, "
            f"wanted {layout!r}")
    if expect_geom is not None and header.get("geom") != expect_geom:
        raise ValueError(
            f"cache at {path} was built with {header.get('geom')}, "
            f"wanted {expect_geom}; delete it or use another path")
    dtype = resolve_dtype(header["dtype"])
    shape = tuple(header["shape"])
    mm = np.memmap(bin_path(path), dtype=dtype, mode="r", shape=shape)
    return mm, header


def shard_rows(n_rows: int, n_shards: int, shard: int) -> tuple[int, int]:
    """Shard-aware slicing: the contiguous ``[lo, hi)`` row range shard
    ``shard`` owns (rows divide the shards exactly — the no-padding-rows
    memmap contract every builder enforces)."""
    if n_rows % n_shards:
        raise ValueError(
            f"{n_rows} cache rows do not divide {n_shards} shards")
    per = n_rows // n_shards
    return shard * per, (shard + 1) * per


def shard_view(mm: np.ndarray, n_shards: int, shard: int) -> np.ndarray:
    """Zero-copy view of one shard's contiguous row range."""
    lo, hi = shard_rows(mm.shape[0], n_shards, shard)
    return mm[lo:hi]


def sweep_stale_tmp(path: str) -> None:
    """Remove tmp orphans of CRASHED generations of THIS cache. Globs
    are anchored to the exact artifact names — a bare ``path + '*'``
    would match a sibling cache sharing the prefix (``/data/cache`` vs
    ``/data/cache_big``) and yank its live tmp files. Age-gated so a
    concurrent live generator (minutes old) is never swept."""
    # tda: ignore[TDA001] -- compared against file MTIMES (wall-clock
    # domain by definition); never feeds a replayed value
    now = time.time()
    for pat in (bin_path(path) + ".tmp.*", meta_path(path) + ".tmp.*",
                path + ".*.tmp.*"):
        # tda: ignore[TDA002] -- unlink order is irrelevant: each
        # orphan is removed independently, nothing downstream sees it
        for stale in glob.glob(pat):
            try:
                if now - os.path.getmtime(stale) > STALE_TMP_SECONDS:
                    os.remove(stale)
            except OSError:
                pass  # a concurrent generator may have just published


def build_cache(path: str, *, header: dict, write_bin, aux=()):
    """Generate and ATOMICALLY publish a cache; returns the read-only
    reopened ``(memmap, header)``.

    ``write_bin(memmap)`` fills the ``header['shape']`` memmap (opened
    ``w+`` in the header dtype) — it may call
    ``telemetry.events.mark`` per chunk so a multi-minute generation
    reads as progress, not a stall. ``aux`` is a sequence of
    ``(name, write_fn)``: each payload is written via
    ``write_fn(tmp_path)`` and published (atomically, BEFORE the bin)
    as ``<path>.<name>``.

    Content MUST be deterministic in the header: two concurrent
    builders both publish, the last rename wins, and either winner is
    byte-identical. The whole build runs inside a
    ``data:cache_build`` telemetry span. A transient ``OSError``
    (including the ``cache:write`` injection point's) retries the whole
    generate+publish attempt in place (:data:`BUILD_RETRIES` attempts —
    determinism makes a re-run byte-identical, so retrying from scratch
    is always safe).
    """
    from tpu_distalg.telemetry.supervisor import supervised

    directory = os.path.dirname(os.path.abspath(path))
    os.makedirs(directory, exist_ok=True)
    sweep_stale_tmp(path)
    dtype = resolve_dtype(header["dtype"])
    shape = tuple(header["shape"])
    tmp_tag = f".tmp.{os.getpid()}.{uuid.uuid4().hex[:8]}"
    bin_tmp = bin_path(path) + tmp_tag
    meta_tmp = meta_path(path) + tmp_tag
    aux_tmps = [(aux_path(path, name), aux_path(path, name) + tmp_tag, fn)
                for name, fn in aux]
    tmps = [bin_tmp, meta_tmp] + [t for _, t, _ in aux_tmps]

    def build_once():
        faults.inject("cache:write")
        mm = np.memmap(bin_tmp, dtype=dtype, mode="w+", shape=shape)
        write_bin(mm)
        mm.flush()
        del mm
        for final, tmp, fn in aux_tmps:
            fn(tmp)
            os.replace(tmp, final)
        os.replace(bin_tmp, bin_path(path))
        with open(meta_tmp, "w") as f:
            json.dump(header, f)
        os.replace(meta_tmp, meta_path(path))

    try:
        with tevents.span("data:cache_build", path=path,
                          layout=header.get("layout"),
                          bytes=int(np.prod(shape)) * dtype.itemsize):
            supervised(build_once, phase="cache:write",
                       retries=BUILD_RETRIES,
                       backoff=BUILD_BACKOFF_SECONDS,
                       backoff_cap=BUILD_BACKOFF_SECONDS, jitter=0.0,
                       retry_on=(OSError,),
                       failure_counter="cache.write_failures",
                       log=lambda m: None)
    finally:
        # a failed generation must not orphan multi-GB tmp bytes
        # (kill -9 still can — sweep_stale_tmp catches those next call)
        for leftover in tmps:
            try:
                os.remove(leftover)
            except OSError:
                pass  # already renamed away (success) or never created
    return open_cache(path, layout=header.get("layout"),
                      expect_geom=header.get("geom"))


def open_or_build(path: str, *, header: dict, write_bin, aux=(),
                  legacy_geom: dict | None = None):
    """The create-or-reopen entry every builder uses: a complete cache
    with a matching header reopens at O(ms); anything else generates
    (mismatched geometry raises from :func:`open_cache` first, loudly).
    ``legacy_geom`` flows through to :func:`open_cache` so pre-versioned
    caches reopen instead of erroring on the header change."""
    if exists(path):
        return open_cache(path, layout=header.get("layout"),
                          expect_geom=header.get("geom"),
                          legacy_geom=legacy_geom)
    return build_cache(path, header=header, write_bin=write_bin, aux=aux)
