"""Out-of-core sharded datasets — the RDD replacement, for every workload.

The reference leans on Spark's RDD to make datasets bigger than memory
a non-problem (``.cache()`` is a hint; partitions spill and stream —
SURVEY §2.2). This package owns that capability ONCE, as a subsystem,
instead of per-trainer:

  ``sharded``   :class:`ShardedDataset` — one block-addressable row
                matrix behind three interchangeable placements
                (``resident`` on-device / ``virtual`` host-RAM /
                ``streamed`` disk-memmap), staging bitwise-identical
                device batches from any of them.
  ``cache``     the versioned packed-cache disk format: atomic publish
                (tmp + rename, header LAST), layout/version/dtype
                header, shard-aware slicing, stale-tmp sweep.
  ``pipeline``  the prefetch engine: one-deep background host-gather +
                double-buffered ``device_put`` so gather ∥ H2D ∥
                compute, plus the host-side threefry block sampler that
                keeps streamed trajectories bitwise-equal to resident
                ones.
  ``builders``  deterministic dataset builders (k-means mixture points,
                ALS rank-k rating rows) that place the same bytes
                behind whichever backend the ``--data-backend`` CLI
                knob asks for.

Consumers: ``models/ssgd_stream`` (ported onto this package),
``models/kmeans.fit_minibatch`` and ``models/als.fit_streamed`` (the
>HBM paths this subsystem opened), ``bench.py``, ``cli.py``.
Every pipeline stage emits telemetry (``data:gather`` / ``data:h2d`` /
``data:cache_build`` spans, ``data.*`` counters) so ``tda report``
shows where a streamed run spends its time.
"""

from tpu_distalg.data.sharded import (
    BACKENDS,
    ShardedDataset,
    block_geometry,
)
from tpu_distalg.data.pipeline import (
    Prefetcher,
    make_host_block_sampler,
    stream_staged,
)
from tpu_distalg.data import builders, cache

__all__ = [
    "BACKENDS",
    "Prefetcher",
    "ShardedDataset",
    "block_geometry",
    "builders",
    "cache",
    "make_host_block_sampler",
    "stream_staged",
]
