"""tpu-distalg — a TPU-native distributed-algorithms framework.

A from-scratch JAX/XLA re-design of the capability surface of
orion-orion/Distributed-Algorithm-PySpark: the PySpark RDD execution layer
(parallelize / broadcast / treeAggregate / reduceByKey / join / shuffle) is
replaced by a device-mesh runtime built on sharded ``jax.Array``s, ``shard_map``
and XLA collectives over ICI/DCN, and the ten reference workloads (five
data-parallel optimizers, k-means, PageRank, transitive closure, ALS, Monte
Carlo) are re-implemented as whole-loop-compiled SPMD programs.

Layer map (SURVEY.md §7):
    parallel/  — mesh/runtime core + collectives/dataflow layer (replaces Spark)
    data/      — out-of-core sharded datasets: ShardedDataset with
                 resident/virtual/streamed backends, the packed-cache disk
                 format, the prefetch pipeline (replaces RDD spill/stream)
    ops/       — jittable numeric kernels (replaces the per-script NumPy lambdas)
    models/    — workload entry points (replaces the reference's __main__ scripts)
    utils/     — PRNG, datasets, metrics, plotting, checkpointing
    telemetry/ — structured JSONL runtime events, heartbeat/stall detection,
                 supervised execution (deadline/retry/backoff/degrade),
                 `tda report` log summarization
    faults/    — deterministic seeded fault injection at every I/O seam,
                 graceful SIGTERM/SIGINT preemption, the `tda chaos`
                 bitwise-recovery harness
"""

from tpu_distalg import data, faults, ops, parallel, telemetry, utils

__version__ = "0.1.0"

__all__ = ["data", "faults", "ops", "parallel", "telemetry", "utils",
           "__version__"]
