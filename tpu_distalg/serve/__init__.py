"""Online serving layer — models answer requests, not just train.

Every workload used to end at a converged metric on disk; this package
is the half of the north star that answers a request. The shape is a
request-level micro-batching front end over the artifacts the training
workloads already checkpoint (``utils/checkpoint.py``):

  bounded queue → deadline-or-size dispatch → pad to a jit-stable
  batch shape → ONE batched predict (one host sync per BATCH, never
  per request) → scatter replies

Pieces:

  * :mod:`~tpu_distalg.serve.batcher` — the queue/dispatch loop
    (:class:`MicroBatcher`): bounded queue (full = shed, reply carries
    :class:`ServeOverloadError` — the server degrades instead of
    dying), every blocking wait carries a timeout (TDA060 polices
    both), per-batch telemetry spans and ``serve.*`` counters;
  * :mod:`~tpu_distalg.serve.artifacts` — checkpoint → servable model:
    LR scoring, k-means assignment, and ALS top-k recommendation
    through the fused Pallas matmul+top-k kernel
    (``ops/pallas_topk.py``) with item factors sharded over the mesh
    model axis and per-shard candidates merged via
    ``comms.ring_allgather`` (``8·B·k·(S−1)`` wire bytes per batch);
  * :mod:`~tpu_distalg.serve.server` — :class:`Server`: one batcher
    per served model, aggregate latency stats (p50/p99/QPS), the
    closed-loop load generator bench.py and ``tda serve`` drive.

Padding is provably inert: a batch is always padded to exactly
``max_batch`` rows, so batched and unbatched requests run the SAME
compiled program and every reply is bitwise-equal to a single-request
submission (tests/test_serve.py pins it per served model).
"""

from tpu_distalg.serve.artifacts import (
    ServedModel,
    als_model,
    kmeans_model,
    load_artifact,
    lr_model,
)
from tpu_distalg.serve.batcher import (
    MicroBatcher,
    Reply,
    ServeClosedError,
    ServeOverloadError,
)
from tpu_distalg.serve.server import ServeConfig, Server

__all__ = [
    "MicroBatcher",
    "Reply",
    "ServeClosedError",
    "ServeConfig",
    "ServeOverloadError",
    "ServedModel",
    "Server",
    "als_model",
    "kmeans_model",
    "load_artifact",
    "lr_model",
]
