"""Request-level micro-batching: bounded queue → deadline-or-size
dispatch → one batched predict → scatter replies.

Liveness discipline (the same contract ``data/pipeline.Prefetcher``
earned, now lint-enforced by TDA060 for this package): the request
queue is BOUNDED — a full queue sheds the request with
:class:`ServeOverloadError` instead of growing without limit — and
every blocking ``get`` carries a timeout, so the dispatch thread can
always observe the stop flag and a wedged producer can never hang the
server silently.

Host-sync discipline (TDA011's invariant, applied to serving): the
dispatch loop performs exactly ONE device synchronization per BATCH —
the predictor's single ``np.asarray`` fetch — never one per request.
Replies are scattered host-side from that one fetched array.

Fault seams: staging a micro-batch is the serving analogue of a data
gather, so dispatch runs through the existing ``data:gather`` injection
point — an injected (or real) failure fails THAT batch's replies and
the loop keeps serving (``tda chaos --workload serve`` proves requests
retried after a shed/failed batch recover bitwise-identical replies).
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time

from tpu_distalg import faults
from tpu_distalg.telemetry import events as tevents

#: idle poll interval for the dispatch loop's first-request wait: the
#: bound that lets the loop re-check the stop flag (a bare blocking
#: get() could sleep forever on an idle server — the TDA060 shape)
POLL_SECONDS = 0.05

#: latency samples kept per batcher (enough for stable p99 at bench
#: scale; a long-lived server keeps the newest window)
MAX_LATENCY_SAMPLES = 200_000


class ServeOverloadError(RuntimeError):
    """The bounded request queue is full — this request was SHED.

    Shedding is the degrade-not-die contract: the server stays live and
    the client decides (retry with backoff, or drop). Carried inside
    the :class:`Reply` rather than raised at ``submit`` so every
    request has a uniform reply-side error surface."""


class ServeClosedError(RuntimeError):
    """The batcher is shutting down; the request was not served."""


class Reply:
    """One request's reply slot: a threading.Event the dispatch thread
    resolves exactly once with a value or an error. ``latency_s`` is
    submit→resolve wall time (monotonic), recorded for the p50/p99
    stats."""

    __slots__ = ("_event", "_value", "_error", "_t_submit", "latency_s")

    def __init__(self):
        self._event = threading.Event()
        self._value = None
        self._error: BaseException | None = None
        self._t_submit = time.perf_counter()
        self.latency_s: float | None = None

    def _resolve(self, value=None, error: BaseException | None = None):
        self.latency_s = time.perf_counter() - self._t_submit
        self._value = value
        self._error = error
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    @property
    def error(self) -> BaseException | None:
        """The reply's error (None while pending or on success) —
        non-raising inspection for shed-aware clients."""
        return self._error

    def result(self, timeout: float = 30.0):
        """Wait (bounded) for the reply; raises the request's error
        (e.g. :class:`ServeOverloadError` when shed)."""
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"no reply within {timeout}s — server wedged or closed?")
        if self._error is not None:
            raise self._error
        return self._value


@dataclasses.dataclass
class BatcherStats:
    """Mutated only under the owning batcher's lock; read via
    :meth:`MicroBatcher.snapshot`."""

    requests: int = 0
    replies: int = 0
    batches: int = 0
    shed: int = 0
    failed_batches: int = 0
    failed_requests: int = 0
    max_queue_depth: int = 0
    latencies_s: list = dataclasses.field(default_factory=list)


class MicroBatcher:
    """One served model's queue + dispatch loop.

    ``predict(payloads)`` receives the list of raw request payloads
    (1 ≤ len ≤ ``max_batch``) and returns one reply value per payload;
    it owns the pad-to-jit-stable-shape and the single per-batch host
    sync (``serve/artifacts.py`` builds it). Dispatch fires when the
    batch hits ``max_batch`` OR ``max_delay_ms`` has passed since the
    batch's first request — a lone request is never parked waiting for
    traffic that may not come (the deadline test pins it).
    """

    def __init__(self, name: str, predict, *, max_batch: int = 16,
                 max_delay_ms: float = 5.0, queue_depth: int = 128):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if queue_depth < 1:
            raise ValueError(
                f"queue_depth must be >= 1, got {queue_depth}")
        self.name = name
        self.max_batch = int(max_batch)
        self.max_delay_s = float(max_delay_ms) / 1e3
        self.queue_depth = int(queue_depth)
        self._predict = predict
        self._q: queue.Queue = queue.Queue(maxsize=queue_depth)
        self._lock = threading.Lock()
        self._stats = BatcherStats()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"serve-batch-{name}")
        self._thread.start()

    # ------------------------------------------------------------- API

    def submit(self, payload) -> Reply:
        """Enqueue one request. Never blocks: a full queue SHEDS the
        request (reply resolves with :class:`ServeOverloadError`) — the
        bounded-queue degrade contract."""
        reply = Reply()
        if self._stop.is_set():
            reply._resolve(error=ServeClosedError(
                f"{self.name}: batcher closed"))
            return reply
        try:
            self._q.put_nowait((payload, reply))
        except queue.Full:
            with self._lock:
                self._stats.shed += 1
            tevents.counter("serve.shed")
            tevents.emit("serve_shed", model=self.name,
                         queue_depth=self.queue_depth)
            reply._resolve(error=ServeOverloadError(
                f"{self.name}: request queue full "
                f"(depth {self.queue_depth}) — shed; retry with backoff"))
            return reply
        if self._stop.is_set():
            # close() raced past the check above between our stop check
            # and the put: its drain may already be done, so nobody
            # would ever read this entry — sweep the queue ourselves
            # (every drained reply resolves exactly once: each queue
            # item is popped by exactly one drainer)
            self._drain_closed()
            return reply
        with self._lock:
            self._stats.requests += 1
            depth = self._q.qsize()
            if depth > self._stats.max_queue_depth:
                self._stats.max_queue_depth = depth
        return reply

    def snapshot(self) -> BatcherStats:
        with self._lock:
            return dataclasses.replace(
                self._stats, latencies_s=list(self._stats.latencies_s))

    def close(self, timeout: float = 10.0):
        """Stop the dispatch loop (drains in-flight work first), then
        fail anything still queued with :class:`ServeClosedError`."""
        self._stop.set()
        self._thread.join(timeout)
        self._drain_closed()

    def _drain_closed(self):
        """Fail everything queued after the stop flag is up. Shared by
        :meth:`close` and the ``submit`` race path (a request enqueued
        between close()'s stop-set and its drain must not hang until
        the client's reply timeout)."""
        while True:
            try:
                _, reply = self._q.get_nowait()
            except queue.Empty:
                break
            reply._resolve(error=ServeClosedError(
                f"{self.name}: batcher closed with request queued"))

    # ---------------------------------------------------- dispatch loop

    def _loop(self):
        while True:
            try:
                first = self._q.get(timeout=POLL_SECONDS)
            except queue.Empty:
                if self._stop.is_set():
                    return
                continue
            batch = [first]
            deadline = time.monotonic() + self.max_delay_s
            while len(batch) < self.max_batch:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    batch.append(self._q.get(timeout=remaining))
                except queue.Empty:
                    break  # deadline hit with a partial batch
            self._dispatch(batch)

    def _dispatch(self, batch):
        payloads = [p for p, _ in batch]
        replies = [r for _, r in batch]
        try:
            with tevents.span("serve:batch", model=self.name,
                              n=len(batch)):
                # staging the micro-batch is the serving analogue of a
                # data gather — same chaos seam, same degrade proof
                faults.inject("data:gather")
                out = self._predict(payloads)
        except Exception as e:  # noqa: BLE001 — a batch failure must
            #                     never kill the dispatch loop: fail
            #                     THESE replies, keep serving
            with self._lock:
                self._stats.batches += 1
                self._stats.failed_batches += 1
                self._stats.failed_requests += len(batch)
            # a failed batch was still a DISPATCHED batch: keep the
            # report-line counters in step with BatcherStats.batches
            tevents.counter("serve.requests", len(batch))
            tevents.counter("serve.batches")
            tevents.counter("serve.failed_batches")
            tevents.emit("serve_batch_failed", model=self.name,
                         n=len(batch), error=f"{type(e).__name__}: {e}")
            for r in replies:
                r._resolve(error=e)
            return
        for r, value in zip(replies, out):
            r._resolve(value=value)
        with self._lock:
            self._stats.batches += 1
            self._stats.replies += len(batch)
            lat = self._stats.latencies_s
            for r in replies:
                lat.append(r.latency_s)
            if len(lat) > MAX_LATENCY_SAMPLES:
                del lat[:len(lat) - MAX_LATENCY_SAMPLES]
        tevents.counter("serve.requests", len(batch))
        tevents.counter("serve.batches")
