"""The serving front end: one :class:`MicroBatcher` per served model,
aggregate latency/throughput stats, and the closed-loop load generator
``tda serve`` and bench.py drive.

A :class:`Server` is in-process by design — the request surface is
``submit(model, payload) -> Reply`` — because the interesting serving
problems this repo owns are BELOW the socket: micro-batching to
jit-stable shapes, one device sync per batch, sharded retrieval with a
sparse candidate merge, shed-don't-die overload behavior, and honest
latency accounting. Any RPC veneer composes on top of ``submit``.
"""

from __future__ import annotations

import dataclasses
import threading
import time

import numpy as np

from tpu_distalg.serve import artifacts as serve_artifacts
from tpu_distalg.serve.batcher import MicroBatcher, Reply
from tpu_distalg.telemetry import events as tevents


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Serving knobs (the ``tda serve`` CLI mirrors these 1:1)."""

    max_batch: int = 16          # dispatch when this many queued …
    max_delay_ms: float = 5.0    # … or this long after the batch opens
    queue_depth: int = 128       # bounded queue; full = shed
    k_top: int = 10              # ALS: recommendations per request
    merge: str = "sparse"        # ALS shard merge: sparse pairs | dense
    use_fused: bool | None = None  # None: Pallas kernel on TPU only
    block_items: int = 1024      # item rows per kernel tile


class Server:
    """Serve one or more artifacts behind micro-batchers."""

    def __init__(self, mesh, config: ServeConfig = ServeConfig()):
        self.mesh = mesh
        self.config = config
        self._models: dict[str, serve_artifacts.ServedModel] = {}
        self._batchers: dict[str, MicroBatcher] = {}
        self._t0 = time.perf_counter()
        self._closed = False

    # ------------------------------------------------------------ setup

    def add_model(self, model: serve_artifacts.ServedModel,
                  *, warm: bool = True) -> serve_artifacts.ServedModel:
        """Register a model and start its batcher. ``warm`` runs one
        dummy padded batch through the predictor so the jit compile
        happens here, not inside the first request's latency."""
        if model.name in self._models:
            raise ValueError(f"model {model.name!r} already served")
        cfg = self.config
        if warm:
            model.predict_batch([self._dummy_payload(model)],
                                cfg.max_batch)
        self._models[model.name] = model
        self._batchers[model.name] = MicroBatcher(
            model.name,
            lambda payloads, m=model: m.predict_batch(
                payloads, cfg.max_batch),
            max_batch=cfg.max_batch, max_delay_ms=cfg.max_delay_ms,
            queue_depth=cfg.queue_depth)
        tevents.emit("serve_model_added", model=model.name,
                     kind=model.kind, source=model.source,
                     **{k: v for k, v in model.meta.items()
                        if isinstance(v, (int, float, str, bool))})
        return model

    def add_artifact(self, path: str, *, name: str | None = None,
                     warm: bool = True) -> serve_artifacts.ServedModel:
        """Load a training checkpoint directory (see
        ``artifacts.load_artifact``) and serve it."""
        cfg = self.config
        model = serve_artifacts.load_artifact(
            path, self.mesh, name=name, k_top=cfg.k_top,
            merge=cfg.merge, use_fused=cfg.use_fused,
            block_items=cfg.block_items)
        return self.add_model(model, warm=warm)

    @staticmethod
    def _dummy_payload(model: serve_artifacts.ServedModel):
        if model.kind == "lr":
            return np.zeros((model.meta["d"],), np.float32)
        if model.kind == "kmeans":
            return np.zeros((model.meta["dim"],), np.float32)
        return np.int32(0)  # als: user id

    # ---------------------------------------------------------- serving

    @property
    def models(self):
        return dict(self._models)

    def submit(self, name: str, payload) -> Reply:
        batcher = self._batchers.get(name)
        if batcher is None:
            raise KeyError(
                f"no served model {name!r} (have: "
                f"{', '.join(sorted(self._batchers)) or 'none'})")
        return batcher.submit(payload)

    # ------------------------------------------------------------ stats

    def stats(self) -> dict:
        """Aggregate serving stats: totals, shed/failure counts, the
        latency percentiles, and the lifetime QPS."""
        per_model = {}
        all_lat: list[float] = []
        totals = dict(requests=0, replies=0, batches=0, shed=0,
                      failed_batches=0, failed_requests=0,
                      max_queue_depth=0)
        for name, b in self._batchers.items():
            s = b.snapshot()
            all_lat.extend(s.latencies_s)
            rec = {k: getattr(s, k) for k in totals}
            rec["mean_batch_fill"] = (
                round(s.replies / s.batches, 2) if s.batches else 0.0)
            per_model[name] = rec
            for k in totals:
                if k == "max_queue_depth":
                    totals[k] = max(totals[k], rec[k])
                else:
                    totals[k] += rec[k]
        elapsed = time.perf_counter() - self._t0
        lat_ms = np.asarray(all_lat, np.float64) * 1e3
        def pct(q):
            if not len(lat_ms):
                return 0.0
            return float(round(np.percentile(lat_ms, q), 3))
        return {
            **totals,
            "elapsed_s": round(elapsed, 3),
            "qps": (round(totals["replies"] / elapsed, 2)
                    if elapsed > 0 else 0.0),
            "p50_ms": pct(50), "p99_ms": pct(99),
            "mean_ms": (float(round(lat_ms.mean(), 3))
                        if len(lat_ms) else 0.0),
            "models": per_model,
        }

    def emit_counters(self) -> dict:
        """Flush the aggregate stats into telemetry: ``serve.qps`` /
        ``serve.p50_ms`` / ``serve.p99_ms`` / ``serve.queue_depth``
        gauges + the request/batch/shed counters — the ``tda report``
        serving line reads exactly these."""
        s = self.stats()
        tevents.gauge("serve.qps", s["qps"])
        tevents.gauge("serve.p50_ms", s["p50_ms"])
        tevents.gauge("serve.p99_ms", s["p99_ms"])
        tevents.gauge("serve.queue_depth", s["max_queue_depth"])
        return s

    def close(self):
        if self._closed:
            return
        self._closed = True
        for b in self._batchers.values():
            b.close()


def run_closed_loop(server: Server, name: str, payloads, *,
                    concurrency: int = 4, retries: int = 0,
                    retry_backoff_s: float = 0.002,
                    timeout: float = 60.0):
    """Closed-loop load generator: ``concurrency`` workers each submit
    their slice of ``payloads`` sequentially (submit → wait for the
    reply → next request — the classic closed loop, so offered load
    tracks service rate instead of overrunning it).

    ``retries`` > 0 makes workers resubmit a shed/failed request (after
    ``retry_backoff_s``) — the client half of the shed-don't-die
    contract, and what lets a chaos run end with a complete,
    bitwise-comparable reply set. Returns ``(results, info)`` where
    ``results[j]`` is request j's reply value (or ``None`` if it still
    failed after the retry budget) and ``info`` carries qps over the
    generator's own window plus error/retry counts.
    """
    results = [None] * len(payloads)
    errors = [None] * len(payloads)
    counts = {"retries": 0, "failed": 0}
    lock = threading.Lock()

    def worker(idxs):
        for j in idxs:
            attempt = 0
            while True:
                reply = server.submit(name, payloads[j])
                try:
                    value = reply.result(timeout)
                    with lock:
                        results[j] = value
                        errors[j] = None
                    break
                except Exception as e:  # noqa: BLE001 — shed/failed
                    #                     replies are data here, and the
                    #                     generator must finish its run
                    with lock:
                        errors[j] = e
                    if attempt >= retries:
                        with lock:
                            counts["failed"] += 1
                        break
                    attempt += 1
                    with lock:
                        counts["retries"] += 1
                    time.sleep(retry_backoff_s)

    concurrency = max(1, min(concurrency, len(payloads) or 1))
    slices = [list(range(w, len(payloads), concurrency))
              for w in range(concurrency)]
    threads = [threading.Thread(target=worker, args=(s,), daemon=True,
                                name=f"serve-load-{w}")
               for w, s in enumerate(slices)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - t0
    n_ok = sum(1 for e in errors if e is None)
    info = {
        "elapsed_s": round(elapsed, 4),
        "qps": round(n_ok / elapsed, 2) if elapsed > 0 else 0.0,
        "ok": n_ok,
        "failed": counts["failed"],
        "retries": counts["retries"],
        "concurrency": concurrency,
    }
    return results, info
