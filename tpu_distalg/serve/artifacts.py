"""Checkpoint → servable model: the artifact side of the serving layer.

The training workloads already persist their converged state through
``utils/checkpoint.py`` (tag + state leaves + CRC footer); this module
turns those files — or in-memory arrays — into :class:`ServedModel`\\ s
the :class:`~tpu_distalg.serve.server.Server` can answer requests from:

  * LR-family tags (``lr``/``ssgd``/``ma``/``bmuf``/``easgd``/
    ``local_sgd``): probability scoring, payload = one (d,) feature row;
  * ``kmeans_*``: nearest-center assignment, payload = one (dim,) point;
  * ``als``: top-k item recommendation, payload = one user id. The
    headline path: user factor rows × the item-factor matrix through
    the fused Pallas matmul+top-k kernel (``ops/pallas_topk.py``) — the
    full score vector never materializes in HBM — with the item factors
    sharded over the mesh MODEL axis (``parallel/sharding.py`` specs)
    and each shard's k candidates merged through the comms layer's ring
    pair exchange (``comms.ring_allgather``: ``8·B·k·(S−1)`` wire bytes
    per batch, vs ``4·B·N·(S−1)/S`` for the dense all-gather baseline
    kept as ``merge='dense'``).

Every predictor compiles ONE program at the server's fixed
``max_batch`` shape and pads every batch to it, so batched and
unbatched submissions run the identical compiled function — the
padding-inert / bitwise-reply contract the tests pin.

Artifact-load degradation: a checkpoint whose read is corrupted in
flight (the ``ckpt:read`` chaos seam, or a real torn read) is RE-READ
once — transient corruption never demotes the served model — and only
persistent corruption falls back through the quarantine path to an
older step, exactly like training resume does.
"""

from __future__ import annotations

import dataclasses
import functools

import numpy as np

from tpu_distalg.telemetry import events as tevents

#: checkpoint tags whose first state leaf is a weight vector servable
#: as a logistic scorer
_LR_TAG_ROOTS = ("lr", "ssgd", "ma", "bmuf", "easgd", "local_sgd")


@dataclasses.dataclass
class ServedModel:
    """One servable model: ``make_predict(max_batch)`` builds (once per
    batch shape — the server uses exactly one) the padded-batch
    predictor ``predict(payloads) -> [reply, ...]`` that owns the
    jit-stable padding and the single per-batch host sync."""

    name: str
    kind: str                     # "lr" | "kmeans" | "als"
    make_predict: object
    source: str = "memory"
    meta: dict = dataclasses.field(default_factory=dict)
    _cache: dict = dataclasses.field(default_factory=dict, repr=False)

    def predictor(self, max_batch: int):
        fn = self._cache.get(max_batch)
        if fn is None:
            fn = self._cache[max_batch] = self.make_predict(max_batch)
        return fn

    def predict_batch(self, payloads, max_batch: int):
        return self.predictor(max_batch)(payloads)

    def predict_one(self, payload, max_batch: int):
        """Unbatched reference: one request through the SAME padded
        compiled program a full batch uses (the bitwise-equality
        contract's other half)."""
        return self.predict_batch([payload], max_batch)[0]


def _stack_pad(payloads, shape: tuple, dtype, max_batch: int,
               what: str) -> np.ndarray:
    """Stack per-request payloads into the fixed (max_batch, *shape)
    batch — zero rows pad the tail (inert: replies are sliced back to
    the true request count; every predictor is row-independent)."""
    if len(payloads) > max_batch:
        raise ValueError(
            f"{what}: batch of {len(payloads)} exceeds max_batch="
            f"{max_batch}")
    out = np.zeros((max_batch,) + shape, dtype)
    for r, p in enumerate(payloads):
        arr = np.asarray(p, dtype)
        if arr.shape != shape:
            raise ValueError(
                f"{what}: payload {r} has shape {arr.shape}, "
                f"want {shape}")
        out[r] = arr
    return out


# --------------------------------------------------------------- models


def lr_model(w, name: str = "lr", *, source: str = "memory"
             ) -> ServedModel:
    """Logistic scorer from a trained weight vector: reply = P(y=1)
    for one (d,) feature row."""
    import jax
    import jax.numpy as jnp

    from tpu_distalg.ops import logistic

    w_dev = jnp.asarray(np.asarray(w), jnp.float32)
    d = int(w_dev.shape[0])

    def make_predict(max_batch: int):
        fn = jax.jit(lambda X: logistic.predict_proba(X, w_dev))

        def predict(payloads):
            X = _stack_pad(payloads, (d,), np.float32, max_batch,
                           f"lr:{name}")
            out = np.asarray(fn(X))  # the ONE host sync for this batch
            return [out[r] for r in range(len(payloads))]

        return predict

    return ServedModel(name=name, kind="lr", make_predict=make_predict,
                       source=source, meta={"d": d})


def kmeans_model(centers, name: str = "kmeans", *,
                 source: str = "memory") -> ServedModel:
    """Cluster assignment from trained centers: reply = nearest-center
    index (int32) for one (dim,) point."""
    import jax
    import jax.numpy as jnp

    from tpu_distalg.ops import kmeans as kops

    c_dev = jnp.asarray(np.asarray(centers), jnp.float32)
    k, dim = int(c_dev.shape[0]), int(c_dev.shape[1])

    def make_predict(max_batch: int):
        fn = jax.jit(lambda X: kops.assign_clusters(X, c_dev))

        def predict(payloads):
            X = _stack_pad(payloads, (dim,), np.float32, max_batch,
                           f"kmeans:{name}")
            out = np.asarray(fn(X))
            return [out[r] for r in range(len(payloads))]

        return predict

    return ServedModel(name=name, kind="kmeans",
                       make_predict=make_predict, source=source,
                       meta={"k": k, "dim": dim})


def _true_rows(M: np.ndarray) -> int:
    """Count of leading rows up to the last non-zero one — recovers the
    TRUE item/user count from a checkpointed factor matrix whose tail
    was zero-padded for sharding (padded rows solve to exactly zero;
    a genuinely all-zero trained factor row is measure-zero)."""
    nz = np.flatnonzero(np.any(np.asarray(M) != 0, axis=1))
    return int(nz[-1]) + 1 if len(nz) else 0


def _true_rows_device(M) -> int:
    """:func:`_true_rows` for a device-resident factor matrix: the
    reduction runs on device and only the resulting SCALAR crosses to
    the host — the old spelling's ``np.asarray(M)`` gathered the whole
    matrix, defeating the device-side handoff."""
    import jax.numpy as jnp

    nz = jnp.any(M != 0, axis=1)
    last = jnp.max(jnp.where(nz, jnp.arange(M.shape[0]) + 1, 0))
    return int(last)


def als_model(U, V, mesh, *, k_top: int = 10, merge: str = "sparse",
              use_fused: bool | None = None, block_items: int = 1024,
              n_items: int | None = None, name: str = "als",
              source: str = "memory") -> ServedModel:
    """Top-k recommendation from ALS factors: payload = one user id
    (int scalar), reply = ``(scores (k_top,) f32, item_ids (k_top,)
    int32)`` in ``lax.top_k`` order.

    The item factors are sharded over the mesh MODEL axis: each shard
    scores only its (N/S, k) slice — through the fused Pallas
    matmul+top-k kernel on TPU (``use_fused=None`` auto-picks; the
    interpret-mode kernel cannot beat native XLA on hosts) — and the
    per-shard candidates merge via ``merge``:

      * ``'sparse'`` (default): ``comms.ring_allgather`` of each
        shard's (value, index) pairs — ``8·B·k_top·(S−1)`` wire bytes
        per batch — then a replicated two-key sort;
      * ``'dense'``: all-gather of the full local score blocks
        (``4·B·N·(S−1)/S`` wire bytes) then a global ``lax.top_k`` —
        the baseline the sparse accounting is measured against.

    ``n_items`` overrides the true catalogue size when the caller knows
    it; by default the zero-padded tail of V is detected and masked so
    padded items can never outscore real ones.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from tpu_distalg.ops import pallas_topk as pt
    from tpu_distalg.parallel import MODEL_AXIS, comms, partition
    from tpu_distalg.parallel.compat import shard_map

    if merge not in ("sparse", "dense"):
        raise ValueError(f"merge must be 'sparse' or 'dense', "
                         f"got {merge!r}")
    # device-resident factors (the in-memory train→serve handoff —
    # bench, chaos, a Server built on the training result) stay on
    # device: the train→serve layout change runs as a device-side
    # reshard below instead of the old np.asarray gather + re-put
    dev_in = isinstance(U, jax.Array) and isinstance(V, jax.Array)
    if dev_in:
        U = jnp.asarray(U, jnp.float32)
        V = jnp.asarray(V, jnp.float32)
    else:
        U = np.asarray(U, np.float32)
        V = np.asarray(V, np.float32)
    if U.shape[1] != V.shape[1]:
        raise ValueError(
            f"U {U.shape} vs V {V.shape}: factor ranks differ")
    if n_items is not None:
        n_true = int(n_items)
    elif dev_in:
        n_true = _true_rows_device(V)  # one scalar D2H, not a gather
    else:
        n_true = _true_rows(V)
    if not 0 < n_true <= V.shape[0]:
        raise ValueError(
            f"n_items={n_true} invalid for V with {V.shape[0]} rows")
    if k_top < 1:
        raise ValueError(f"k_top must be >= 1, got {k_top}")
    on_tpu = next(iter(mesh.devices.flat)).platform == "tpu"
    fused = on_tpu if use_fused is None else bool(use_fused)
    n_model = int(mesh.shape[MODEL_AXIS])
    # pad items so every model shard holds an equal slice; padded rows
    # are zero AND index-masked (>= n_true scores -inf) — doubly inert
    n_pad = -(-V.shape[0] // n_model) * n_model
    if n_pad != V.shape[0]:
        pad = ((0, n_pad - V.shape[0]), (0, 0))
        V = jnp.pad(V, pad) if dev_in else np.pad(V, pad)
    local_n = n_pad // n_model

    if dev_in:
        # the train-layout → serve-layout seam, device-side: U
        # all-gathers to replicated, V slices to its model shards —
        # the collective program arXiv:2112.01075 argues for, with
        # the wire bytes accounted in the reshard.* counters
        placed = partition.reshard({"U": U, "V": V},
                                   "als_train", "als_serve", mesh)
    else:
        # host factors (a disk artifact): one H2D per leaf direct to
        # the serve layout
        placed = partition.place({"U": U, "V": V}, "als_serve", mesh)
    U_dev, V_dev = placed["U"], placed["V"]

    def _score(q, Vl, off, nv):
        if fused:
            return pt.fused_matmul_topk(
                q, Vl, off, nv, k=k_top, block_items=block_items,
                interpret=not on_tpu)
        return pt.xla_matmul_topk(q, Vl, off, nv, k=k_top)

    if n_model == 1:
        def topk_fn(ids, Uq, Vl):
            return _score(Uq[ids], Vl, 0, n_true)

        fn = jax.jit(topk_fn)
        wire_per_req = 0
    elif merge == "sparse":
        def body(ids, Uq, Vl):
            off = lax.axis_index(MODEL_AXIS) * local_n
            nv = jnp.clip(n_true - off, 0, local_n)
            v, i = _score(Uq[ids], Vl, off, nv)
            all_v, all_i = comms.ring_allgather((v, i), MODEL_AXIS,
                                                n_model)
            return pt.merge_topk_pairs(all_v, all_i, k=k_top)

        # the ring pair exchange + origin-order merge IS replicated by
        # construction (every shard gathers the same pairs and sorts
        # identically); the static checker can't see through ppermute,
        # so the check is off — same call shape as spmd.data_parallel
        fn = jax.jit(shard_map(
            body, mesh, in_specs=(P(), P(), P(MODEL_AXIS, None)),
            out_specs=(P(), P()), check_vma=False))
        wire_per_req = 8 * k_top * (n_model - 1)
    else:
        def body(ids, Uq, Vl):
            off = lax.axis_index(MODEL_AXIS) * local_n
            q = Uq[ids]
            scores = jnp.matmul(q, Vl.T)
            pos = jnp.arange(local_n, dtype=jnp.int32)[None, :] + off
            scores = jnp.where(pos < n_true, scores, -jnp.inf)
            full = lax.all_gather(scores, MODEL_AXIS, axis=1,
                                  tiled=True)
            vals, idx = lax.top_k(full, k_top)
            return vals, idx.astype(jnp.int32)

        fn = jax.jit(shard_map(
            body, mesh, in_specs=(P(), P(), P(MODEL_AXIS, None)),
            out_specs=(P(), P()), check_vma=False))
        wire_per_req = 4 * n_pad * (n_model - 1) // n_model

    def make_predict(max_batch: int):
        wire_per_batch = wire_per_req * max_batch

        def predict(payloads):
            ids = _stack_pad(payloads, (), np.int32, max_batch,
                             f"als:{name}")
            vals, idx = jax.device_get(fn(ids, U_dev, V_dev))
            if wire_per_batch:
                tevents.counter("serve.merge_bytes_wire",
                                wire_per_batch)
            return [(vals[r], idx[r]) for r in range(len(payloads))]

        return predict

    return ServedModel(
        name=name, kind="als", make_predict=make_predict, source=source,
        meta={"n_items": n_true, "n_users": int(U.shape[0]),
              "rank": int(U.shape[1]), "k_top": k_top, "merge": merge,
              "fused": fused, "n_model": n_model,
              "merge_wire_bytes_per_request": wire_per_req})


# ------------------------------------------------------ checkpoint load


def _restore_with_reread(path: str):
    """Load the newest checkpoint, degrading gracefully: a corrupt READ
    (the ``ckpt:read`` seam flips bytes in flight) is re-read once —
    the file on disk is usually intact — and only persistent corruption
    falls back through the quarantine path to an older step."""
    from tpu_distalg.utils import checkpoint as ckpt

    try:
        return ckpt.restore(path)
    except ckpt.CorruptCheckpointError:
        tevents.counter("serve.artifact_reread")
        tevents.emit("serve_artifact_reread", path=path)
        try:
            return ckpt.restore(path)
        except ckpt.CorruptCheckpointError:
            out = ckpt.restore_newest_with_fallback(path)
            if out is None:
                raise FileNotFoundError(
                    f"no restorable checkpoint under {path}") from None
            return out


def load_artifact_state(path: str) -> tuple:
    """The jax-free half of :func:`load_artifact`: restore the newest
    checkpoint (with the re-read degradation), verify the tagged
    format, and return ``(tag_root, state_leaves, step)`` raw. The
    cluster serving replicas (``cluster/serve.py``) ride this — they
    score with host numpy kernels and must not pull a jax mesh into
    every replica process just to read weights."""
    payload, step = _restore_with_reread(path)
    if "tag" not in payload or "state" not in payload:
        raise ValueError(
            f"checkpoint under {path} predates the tagged format — "
            f"re-train with a current build to serve it")
    tag = np.asarray(payload["tag"]).tobytes().decode(errors="replace")
    state = [np.asarray(x) for x in payload["state"]]
    root = tag.split(":", 1)[0]
    tevents.emit("serve_artifact_loaded", path=path, tag=tag, step=step)
    return root, state, step


def load_artifact(path: str, mesh, *, name: str | None = None,
                  k_top: int = 10, merge: str = "sparse",
                  use_fused: bool | None = None,
                  block_items: int = 1024) -> ServedModel:
    """Open a training checkpoint directory as a :class:`ServedModel`,
    dispatching on the checkpoint's workload tag (the same tag
    ``run_segmented`` verifies on resume). The ``tda serve --artifact``
    path — pair it with the ``artifact_path:`` line the training CLIs
    print."""
    root, state, _step = load_artifact_state(path)
    if root in _LR_TAG_ROOTS:
        return lr_model(state[0], name=name or root, source=path)
    if root.startswith("kmeans"):
        return kmeans_model(state[0], name=name or "kmeans",
                            source=path)
    if root == "als":
        return als_model(state[0], state[1], mesh, k_top=k_top,
                         merge=merge, use_fused=use_fused,
                         block_items=block_items,
                         name=name or "als", source=path)
    raise ValueError(
        f"checkpoint under {path} holds workload {root!r} — no serving "
        f"adapter for it (servable: {', '.join(_LR_TAG_ROOTS)}, "
        f"kmeans_*, als)")
