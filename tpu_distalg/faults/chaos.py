"""The chaos harness — ``tda chaos``: prove recovery, don't claim it.

Runs one small real workload TWICE: once undisturbed, once under a
:class:`~tpu_distalg.faults.FaultPlan` with the full recovery stack
armed (``run_with_restarts`` + a checkpoint directory), and asserts the
recovered final state is BITWISE-equal to the undisturbed run. That
single assertion is the whole point of the repo's recovery machinery:
absolute-step PRNG keying makes segmented ≡ straight ≡ crashed-and-
resumed, so any drift under chaos is a real bug, not noise.

Workloads are deliberately tiny (seconds on the CPU mesh) — the value
is the fault schedule, not the FLOPs:

  ``lr``             full-batch logistic regression (checkpointed)
  ``ssgd``           minibatch SGD (checkpointed; PRNG keyed on
                     absolute step)
  ``kmeans``         full-batch Lloyd (checkpointed)
  ``als``            alternating least squares (checkpointed)
  ``kmeans_stream``  minibatch k-means over a virtual-backend
                     ShardedDataset — the prefetch pipeline under
                     chaos (``data:gather`` / ``data:h2d`` faults;
                     stateless, so a restart re-runs from step 0
                     deterministically)
  ``pagerank_stream``  streamed PageRank over a power-law edge-block
                     cache (``tpu_distalg/graphs/``) — the out-of-core
                     frontier sweep under chaos: the block gather/H2D
                     path runs through the same ``data:gather`` /
                     ``data:h2d`` seams, checkpointed so a mid-sweep
                     fault resumes the power iteration bitwise
  ``ssp``            stale-synchronous SSGD (``--sync ssp``,
                     ``tpu_distalg/parallel/ssp.py``) under a
                     straggler + leave/rejoin schedule
                     (``shard:straggle``/``shard:leave`` plan rules).
                     The verdict POLICY differs from every other
                     workload, because the faults here are SEMANTIC
                     inputs, not recoverable I/O errors: a straggled
                     run legitimately walks a different trajectory, so
                     the harness asserts (a) the chaos run CONVERGES
                     within :data:`SSP_CHAOS_ACC_BAND` of the
                     undisturbed run's final accuracy, and (b) the
                     chaos run REPLAYED from its recorded plan is
                     bitwise-identical — determinism survives the
                     asynchrony.
  ``serve``          the online serving layer (``tpu_distalg/serve/``)
                     answering a fixed request sequence: artifact load
                     runs through the ``ckpt:read`` seam (transient
                     corruption re-read, never a demoted model) and
                     every micro-batch dispatch through ``data:gather``
                     (an injected failure fails THAT batch's replies,
                     the server keeps serving, the closed-loop client
                     retries) — recovery is shed-and-retry, and the
                     final reply set must still be bitwise-identical
  ``rowstore``       cluster PageRank through the sharded row store
                     (``tpu_distalg/cluster/rowstore.py``): per-worker
                     sparse rank pulls/pushes through real wire
                     frames, per-commit WAL row-redo records —
                     seeded ``cluster:worker`` / ``cluster:coordinator``
                     (rollback: kill BEFORE the redo record is durable)
                     / ``cluster:ps`` (redo: kill AFTER the record,
                     before the merge applies) / ``cluster:rpc`` faults
                     all recover to a BITWISE-identical rank vector
                     and commit-event digest, dense or compressed wire
  ``cluster``        the multi-process elastic runtime
                     (``tpu_distalg/cluster/``) under a COORDINATOR
                     kill (``cluster:coordinator`` plan rules): the
                     launcher respawns the coordinator on the same
                     port, it recovers from the durable WAL, the
                     surviving workers reconnect and resume their
                     incarnations — and because push acks are
                     deferred until commit, the rolled-back in-flight
                     window re-runs invisibly: the recovered run's
                     final center is BITWISE-identical to the
                     undisturbed run's, with an identical merge/
                     membership event digest (standard bitwise
                     verdict — no convergence band needed)

Used three ways: the ``tda chaos`` CLI subcommand (rc 1 on any
mismatch), ``tests/test_faults.py``'s acceptance grid, and ad-hoc
reproduction of a production fault schedule (`--fault-plan` accepts the
JSONL-recorded plan of a real incident).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from tpu_distalg import faults
from tpu_distalg.telemetry import events as tevents

WORKLOADS = ("lr", "ssgd", "kmeans", "als", "kmeans_stream",
             "pagerank_stream", "serve", "ssp", "cluster",
             "cluster_serve", "rowstore")

#: the serving fleet's availability floor under chaos: the fraction of
#: requests answered on the FIRST client attempt (internal re-routes
#: are transparent and don't count against it; sheds and re-route
#: exhaustion do). A replica kill mid-burst must stay above this —
#: redundancy, not luck. A pure kill plan sits at ~1.0 (re-routes are
#: internal); the headroom below is for the cluster:rpc oserror storm
#: grid, where every request crosses several injectable seams and a
#: fraction legitimately needs one client retry
CLUSTER_SERVE_AVAILABILITY_BAND = 0.85

#: the ssp workload's convergence band: |chaos final acc − undisturbed
#: final acc| must stay inside it (a straggled + leave/rejoin run walks
#: a DIFFERENT deterministic trajectory — bitwise equality is asserted
#: against its own replay instead)
SSP_CHAOS_ACC_BAND = 0.12

# enough restarts to survive a multi-fault schedule without masking a
# deterministic bug forever (a fault that keeps re-firing on @* rules
# still exhausts this and fails loudly)
DEFAULT_MAX_RESTARTS = 3


@dataclasses.dataclass
class ServeChaosResult:
    """The serve workload's comparison surface: the stacked replies for
    the fixed request sequence, plus the degradation evidence (sheds /
    failed batches / client retries) the test asserts actually
    happened. Only ``replies`` enters the bitwise compare — degradation
    COUNTS legitimately differ between runs; the replies must not."""

    replies: np.ndarray
    shed: int
    failed_batches: int
    client_retries: int


@dataclasses.dataclass
class ClusterChaosResult:
    """The cluster workload's comparison surface: the final center
    and the merge/membership event digest (as bytes, so it rides the
    standard bitwise compare). Recovery evidence is carried for the
    tests to assert the kill really fired — it never enters the
    compare (wall clock legitimately differs)."""

    center_w: np.ndarray
    event_digest: np.ndarray
    recoveries: int
    recovery_ms: list


@dataclasses.dataclass
class RowstoreChaosResult:
    """The rowstore workload's comparison surface: the final rank
    vector and the commit-event digest (as bytes, riding the standard
    bitwise compare). Recovery/sparsity evidence is carried for the
    tests' the-kill-really-fired and the-pulls-really-were-sparse
    assertions — never part of the compare."""

    ranks: np.ndarray
    event_digest: np.ndarray
    recoveries: int
    sparse_pull_fraction: float


@dataclasses.dataclass
class ClusterServeChaosResult:
    """The cluster_serve workload's comparison surface: the stacked
    router replies for the fixed request sequence (bitwise — replicas
    score with fixed-shape host kernels, so a re-routed request's
    reply is identical to the undisturbed run's). Availability and the
    degradation counts ride along for the band verdict and the tests'
    the-kill-really-fired assertions; they never enter the compare."""

    replies: np.ndarray
    availability: float
    sheds: int
    reroutes: int
    client_retries: int


@dataclasses.dataclass
class ChaosResult:
    workload: str
    plan_spec: str
    equal: bool
    mismatched: list[str]
    fired: list[tuple[str, int, str]]
    restarts_logged: int

    def verdict(self) -> str:
        fired = ", ".join(f"{p}#{h}={k}" for p, h, k in self.fired) or "-"
        if self.equal:
            return (f"[chaos] OK: {self.workload} recovered bitwise-"
                    f"equal under {len(self.fired)} injected fault(s) "
                    f"({fired}; {self.restarts_logged} restart(s))")
        return (f"[chaos] MISMATCH: {self.workload} diverged in "
                f"{', '.join(self.mismatched)} under injected faults "
                f"({fired}) — a recovery path is broken")


def _leaves(workload: str, res) -> dict[str, np.ndarray]:
    """The bitwise-comparison surface per workload: every array a user
    could consume from the result."""
    if workload in ("lr", "ssgd", "ssp"):
        return {"w": np.asarray(res.w), "accs": np.asarray(res.accs)}
    if workload == "cluster":
        # tda: ignore[TDA100] -- not a checkpoint payload: this is the
        # bitwise-COMPARE surface, and recoveries/recovery_ms are
        # deliberately outside it (wall clock legitimately differs
        # between the disturbed and undisturbed runs — see
        # ClusterChaosResult's docstring)
        return {"center_w": np.asarray(res.center_w),
                "event_digest": np.asarray(res.event_digest)}
    if workload in ("kmeans", "kmeans_stream"):
        return {"centers": np.asarray(res.centers)}
    if workload == "als":
        return {"U": np.asarray(res.U), "V": np.asarray(res.V),
                "rmse_history": np.asarray(res.rmse_history)}
    if workload == "pagerank_stream":
        return {"ranks": np.asarray(res.ranks)}
    if workload == "rowstore":
        # tda: ignore[TDA100] -- not a checkpoint payload: the
        # bitwise-COMPARE surface; recoveries/sparsity stay outside it
        # (see RowstoreChaosResult's docstring)
        return {"ranks": np.asarray(res.ranks),
                "event_digest": np.asarray(res.event_digest)}
    if workload == "serve":
        return {"replies": np.asarray(res.replies)}
    if workload == "cluster_serve":
        return {"replies": np.asarray(res.replies)}
    raise ValueError(f"unknown chaos workload {workload!r}; choose from "
                     f"{WORKLOADS}")


def _make_runner(workload: str, mesh, n_iterations: int | None,
                 checkpoint_every: int | None, workdir: str,
                 spawn: str = "thread", comm: str = "dense"):
    """Build ``run(checkpoint_dir) -> result`` for one workload, small
    defaults. ``checkpoint_dir=None`` runs unsegmented (kmeans_stream —
    stateless, restart-from-scratch recovery). ``workdir`` hosts any
    on-disk artifact the workload needs beyond checkpoints (the
    streamed graph cache). ``spawn`` and ``comm`` apply to the cluster
    workload only (thread-mode workers for the fast smoke, real
    processes for the genuine kill -9; ``comm`` is the wire schedule
    BOTH runs use — compression must compose with chaos, same
    verdict)."""
    if workload == "cluster":
        from tpu_distalg import cluster as clus
        from tpu_distalg.cluster.local import event_digest

        windows = n_iterations or 8
        every = checkpoint_every or 3

        def run(ckpt_dir):
            # the plan drives the cluster CONFIG (schedules compile
            # plan-pure from it): the undisturbed reference runs with
            # the registry disabled -> no plan -> no kill
            reg = faults.active()
            plan_spec = reg.plan.spec() if reg is not None else None
            cfg = clus.ClusterConfig(
                n_slots=3, n_windows=windows, staleness=3,
                # generous: a slow reconnect on a loaded box must not
                # flip into a readmission and fail the bitwise
                # verdict for the wrong reason
                heartbeat_timeout=15.0, checkpoint_every=every,
                checkpoint_dir=ckpt_dir, plan_spec=plan_spec,
                comm=comm,
                train=clus.TrainTask(n_rows=1024, test_rows=512))
            res = clus.run_local_cluster(cfg, spawn=spawn,
                                         timeout=280.0)
            if res["version"] != windows:
                raise RuntimeError(
                    f"cluster chaos run stopped at window "
                    f"{res['version']}/{windows}")
            return ClusterChaosResult(
                center_w=np.asarray(res["center"]["w"]),
                event_digest=np.frombuffer(
                    bytes.fromhex(event_digest(res)), np.uint8),
                recoveries=int(res.get("coordinator_recoveries", 0)),
                recovery_ms=list(res.get("recovery_ms", [])))
        return run
    if workload == "rowstore":
        import os

        from tpu_distalg import graphs
        from tpu_distalg.cluster import rowstore

        # the cache is built ONCE, outside both runs (the chaos
        # surface is the fleet's pull/push/commit protocol, not the
        # ingest) — small but genuinely sparse: each dst-window worker
        # pulls a strict subset of the rank vector
        path = os.path.join(workdir, "graph", "rowstore")
        graphs.build_powerlaw_block_cache(
            path, n_vertices=512, n_shards=4, avg_in_degree=8.0,
            alpha=1.6, seed=3, block_edges=64)
        iters = n_iterations or 6

        def run(ckpt_dir):
            # the plan drives the fleet CONFIG (point schedules
            # compile plan-pure from it): the undisturbed reference
            # runs registry-disabled -> no plan -> no fault
            reg = faults.active()
            plan_spec = reg.plan.spec() if reg is not None else None
            res = rowstore.run_cluster_pagerank(
                path, rowstore.ClusterPageRankConfig(
                    n_iterations=iters, comm=comm,
                    plan_spec=plan_spec,
                    wal_dir=os.path.join(ckpt_dir, "wal")))
            if res["version"] != iters:
                raise RuntimeError(
                    f"rowstore chaos run stopped at iteration "
                    f"{res['version']}/{iters}")
            return RowstoreChaosResult(
                ranks=np.asarray(res["ranks"]),
                event_digest=np.frombuffer(
                    bytes.fromhex(res["event_digest"]), np.uint8),
                recoveries=int(res["recoveries"]),
                sparse_pull_fraction=float(
                    res["sparse_pull_fraction"]))
        return run
    if workload == "lr":
        from tpu_distalg.models import logistic_regression as m
        from tpu_distalg.utils import datasets

        data = datasets.breast_cancer_split()
        cfg = m.LRConfig(n_iterations=n_iterations or 60)
        every = checkpoint_every or 20

        def run(ckpt_dir):
            return m.train(*data, mesh, cfg, checkpoint_dir=ckpt_dir,
                           checkpoint_every=every)
        return run
    if workload == "ssgd":
        from tpu_distalg.models import ssgd as m
        from tpu_distalg.utils import datasets

        data = datasets.breast_cancer_split()
        cfg = m.SSGDConfig(n_iterations=n_iterations or 90)
        every = checkpoint_every or 30

        def run(ckpt_dir):
            return m.train(*data, mesh, cfg, checkpoint_dir=ckpt_dir,
                           checkpoint_every=every)
        return run
    if workload == "kmeans":
        from tpu_distalg.models import kmeans as m
        from tpu_distalg.utils import datasets

        pts = datasets.gaussian_mixture(4000, k=3, seed=1)
        cfg = m.KMeansConfig(k=3, n_iterations=n_iterations or 9)
        every = checkpoint_every or 3

        def run(ckpt_dir):
            return m.fit(pts, mesh, cfg, checkpoint_dir=ckpt_dir,
                         checkpoint_every=every)
        return run
    if workload == "als":
        from tpu_distalg.models import als as m

        cfg = m.ALSConfig(n_iterations=n_iterations or 6)
        every = checkpoint_every or 2

        def run(ckpt_dir):
            return m.fit(mesh, cfg, checkpoint_dir=ckpt_dir,
                         checkpoint_every=every)
        return run
    if workload == "kmeans_stream":
        from tpu_distalg.data import builders
        from tpu_distalg.models import kmeans as m

        ds, _ = builders.gaussian_points_dataset(
            mesh, 4096, dim=8, k=3, seed=1, block_rows=256,
            backend="virtual")
        cfg = m.KMeansConfig(k=3)
        steps = n_iterations or 8

        def run(ckpt_dir):
            del ckpt_dir  # stateless: recovery = deterministic re-run
            return m.fit_minibatch(ds, cfg, n_steps=steps,
                                   mini_batch_blocks=2)
        return run
    if workload == "pagerank_stream":
        import os

        from tpu_distalg import graphs
        from tpu_distalg.parallel import DATA_AXIS

        n_shards = int(mesh.shape[DATA_AXIS])
        # the cache is built ONCE, outside both runs (its publish path
        # has its own cache:write seam coverage in test_faults) — the
        # chaos surface here is the streamed sweep's gather/H2D path
        path = os.path.join(workdir, "graph", "powerlaw")
        graphs.build_powerlaw_block_cache(
            path, n_vertices=2048, n_shards=n_shards,
            avg_in_degree=8.0, alpha=1.6, seed=1, block_edges=512)
        cfg = graphs.StreamedPageRankConfig(
            n_iterations=n_iterations or 6)
        every = checkpoint_every or 2

        def run(ckpt_dir):
            gd = graphs.open_graph_dataset(path, mesh,
                                           backend="streamed")
            return graphs.run_streamed_pagerank(
                gd, cfg, checkpoint_dir=ckpt_dir,
                checkpoint_every=every)
        return run
    if workload == "ssp":
        from tpu_distalg.models import ssgd as m
        from tpu_distalg.utils import datasets

        data = datasets.breast_cancer_split()
        cfg = m.SSGDConfig(n_iterations=n_iterations or 160,
                           sync="ssp:4")
        every = checkpoint_every or 40

        def run(ckpt_dir):
            return m.train(*data, mesh, cfg, checkpoint_dir=ckpt_dir,
                           checkpoint_every=every)
        return run
    if workload == "cluster_serve":
        from tpu_distalg.cluster import serve as cserve

        # a fixed synthetic center + request sequence: the chaos
        # surface is the serving PLANE (dispatch, re-route, shed,
        # cluster:rpc wire faults), not training — and the fixed-shape
        # host scorers make every reply bitwise-reproducible no matter
        # which replica ends up answering it
        rng = np.random.default_rng(7)
        center = {"centers": rng.normal(
            size=(8, 16)).astype(np.float32)}
        X_req = rng.normal(
            size=(n_iterations or 96, 16)).astype(np.float32)

        def run(ckpt_dir):
            del ckpt_dir  # recovery = re-route + client retry
            fleet = cserve.ServeFleet(cserve.FleetConfig(
                kind="kmeans", n_replicas=3, version=1,
                max_delay_ms=1.0), center).start()
            try:
                # backoff × retries must span the router's revival
                # sweep (hb_interval): an oserror storm can condemn
                # the whole fleet for one beat, and a client that
                # burns its retries inside that beat fails a request
                # the next beat would have answered
                results, info = cserve.run_fleet_closed_loop(
                    fleet, list(X_req), concurrency=4, retries=10,
                    retry_backoff_s=0.05)
                if info["failed"]:
                    # out of retry budget — restartable, not a verdict
                    raise RuntimeError(
                        f"cluster_serve chaos: {info['failed']} "
                        f"request(s) still failed after retries")
                st = fleet.stats()
                return ClusterServeChaosResult(
                    replies=np.stack([np.asarray(v)
                                      for v, _ver, _rid in results]),
                    availability=float(info["availability"]),
                    sheds=int(st["sheds"]),
                    reroutes=int(st["reroutes"]),
                    client_retries=int(info["retries"]))
            finally:
                fleet.stop()
        return run
    if workload == "serve":
        import os

        from tpu_distalg.models import logistic_regression as lrm
        from tpu_distalg.utils import datasets

        # the artifact is trained ONCE, outside both runs (its write
        # path has its own ckpt:write chaos coverage) — the chaos
        # surface here is the serving stack: artifact LOAD (ckpt:read)
        # and micro-batch dispatch (data:gather)
        data = datasets.breast_cancer_split()
        artifact_dir = os.path.join(workdir, "artifact")
        lrm.train(*data, mesh,
                  lrm.LRConfig(n_iterations=n_iterations or 30),
                  checkpoint_dir=artifact_dir, checkpoint_every=10)
        X_req = np.asarray(data[2], np.float32)[:24]  # fixed test rows

        def run(ckpt_dir):
            del ckpt_dir  # recovery = shed + client retry, no resume
            from tpu_distalg import serve as serve_pkg
            from tpu_distalg.serve.server import run_closed_loop

            srv = serve_pkg.Server(mesh, serve_pkg.ServeConfig(
                max_batch=4, max_delay_ms=2.0, queue_depth=8))
            try:
                srv.add_artifact(artifact_dir, name="lr")
                results, info = run_closed_loop(
                    srv, "lr", list(X_req), concurrency=2, retries=8,
                    retry_backoff_s=0.01)
                if info["failed"]:
                    # out of retry budget — restartable, not a verdict
                    raise RuntimeError(
                        f"serve chaos: {info['failed']} request(s) "
                        f"still failed after retries")
                st = srv.stats()
                return ServeChaosResult(
                    replies=np.stack([np.asarray(r) for r in results]),
                    shed=st["shed"],
                    failed_batches=st["failed_batches"],
                    client_retries=info["retries"])
            finally:
                srv.close()
        return run
    raise ValueError(f"unknown chaos workload {workload!r}; choose from "
                     f"{WORKLOADS}")


def run_chaos(workload: str, mesh, *, plan, workdir: str,
              n_iterations: int | None = None,
              checkpoint_every: int | None = None,
              max_restarts: int = DEFAULT_MAX_RESTARTS,
              spawn: str = "thread", comm: str = "dense",
              logger=None) -> ChaosResult:
    """The harness core: undisturbed run, chaos run, bitwise compare.

    ``plan`` is a :class:`~tpu_distalg.faults.FaultPlan` or spec string.
    Both runs use fresh checkpoint directories under ``workdir``; the
    chaos run executes under ``run_with_restarts(max_restarts)``. The
    process-global fault registry is left DISABLED on return (whatever
    it was before — a chaos run is a self-contained experiment)."""
    import os

    from tpu_distalg.utils import checkpoint as ckpt

    if isinstance(plan, str):
        plan = faults.FaultPlan.parse(plan)
    log = logger or (lambda m: None)
    # injection OFF before ANY experiment I/O, not just the reference
    # run: the serve runner trains its artifact inside _make_runner,
    # and an ambient registry armed by the caller must not corrupt the
    # shared artifact or consume its own hit counters out of schedule
    faults.configure(False)
    runner = _make_runner(workload, mesh, n_iterations, checkpoint_every,
                          workdir, spawn=spawn, comm=comm)
    # kmeans_stream recovers by deterministic re-run, serve by
    # shed-and-client-retry, cluster_serve by re-route-and-retry —
    # none consumes a checkpoint dir
    uses_ckpt = workload not in ("kmeans_stream", "serve",
                                 "cluster_serve")

    def dirpath(name):
        d = os.path.join(workdir, name)
        return d if uses_ckpt else None

    # undisturbed reference first
    tevents.mark("chaos:reference", emit_event=False)
    ref = runner(dirpath("ref"))

    # chaos run: fresh registry (invocation counters at zero) so the
    # schedule replays identically on every invocation of the harness
    reg = faults.configure(plan)
    tevents.mark("chaos:faulted", emit_event=False)
    restart_log: list[str] = []
    try:
        got = ckpt.run_with_restarts(
            lambda: runner(dirpath("chaos")),
            max_restarts=max_restarts,
            logger=lambda m: (restart_log.append(m), log(m)))
    finally:
        fired = list(reg.fired)
        faults.configure(False)

    ref_leaves = _leaves(workload, ref)
    got_leaves = _leaves(workload, got)
    if workload == "ssp":
        # SEMANTIC faults (straggle/leave) legitimately change the
        # trajectory: the acceptance is convergence-within-band vs the
        # undisturbed run PLUS bitwise identity vs a replay of the
        # same recorded plan (a third run, fresh registry)
        faults.configure(plan)
        tevents.mark("chaos:replay", emit_event=False)
        try:
            import shutil

            shutil.rmtree(os.path.join(workdir, "chaos"),
                          ignore_errors=True)
            replay = ckpt.run_with_restarts(
                lambda: runner(dirpath("chaos")),
                max_restarts=max_restarts, logger=log)
        finally:
            faults.configure(False)
        rep_leaves = _leaves(workload, replay)
        mismatched = [
            f"replay:{name}" for name, a in got_leaves.items()
            if not np.array_equal(a, rep_leaves[name])]

        def tail_acc(leaves):
            # the breast-cancer SGD endpoint oscillates a few points
            # tick to tick (PR 5's comm phase hit the same thing) — a
            # single-tick compare would flunk healthy runs, so the
            # band is on the LAST-QUARTER mean of the accuracy history
            accs = leaves["accs"]
            return float(np.mean(accs[-max(1, len(accs) // 4):]))

        band = abs(tail_acc(got_leaves) - tail_acc(ref_leaves))
        if band > SSP_CHAOS_ACC_BAND:
            mismatched.append(
                f"band:tail_acc (|Δ|={band:.4f} > "
                f"{SSP_CHAOS_ACC_BAND})")
    else:
        mismatched = [name for name, a in ref_leaves.items()
                      if not np.array_equal(a, got_leaves[name])]
        if workload == "cluster_serve":
            # bitwise replies alone would pass a fleet that answered
            # every request on its fifth retry — availability is the
            # second half of the verdict, checked against a pinned band
            avail = float(got.availability)
            if avail < CLUSTER_SERVE_AVAILABILITY_BAND:
                mismatched.append(
                    f"band:availability ({avail:.4f} < "
                    f"{CLUSTER_SERVE_AVAILABILITY_BAND})")
    result = ChaosResult(
        workload=workload, plan_spec=plan.spec(),
        equal=not mismatched, mismatched=mismatched, fired=fired,
        # the logger also receives "[quarantine] ..." lines — only
        # count actual restart cycles in the verdict
        restarts_logged=sum(1 for m in restart_log
                            if m.startswith("[restart")))
    tevents.emit("chaos_verdict", workload=workload, equal=result.equal,
                 mismatched=mismatched, faults_fired=len(fired))
    return result
