"""Deterministic fault injection — seeded chaos that replays bitwise.

Every recovery path this framework grew (``run_with_restarts`` retry,
corrupt-checkpoint quarantine, deadline-guarded backend init, prefetch
error forwarding) only ran when real infrastructure broke — the r5
outage was diagnosed *after* the fact precisely because failure code is
the least-executed code in the repo. This module turns failure into a
routine, reproducible input: named injection points sit at every I/O
and supervision seam, and a seeded :class:`FaultPlan` decides which
invocation of which point misbehaves and how. The same plan + seed
replays the identical failure sequence, so a chaos run is as
deterministic as a clean one — and the chaos suite can assert the
recovered state is BITWISE-equal to an undisturbed run.

Injection points (wired at the call sites named):

  ``shard:straggle``  SSP schedule compilation
                    (``parallel/ssp.compile_straggle_schedule``) — one
                    probe per (tick, shard) in fixed row-major order,
                    so rule ``@N`` addresses invocation
                    ``tick·n_shards + shard``
  ``shard:leave``   elastic-membership epoch compilation
                    (``parallel/membership.compile_epochs``) — one
                    probe per (window boundary, shard), same ordering
  ``cluster:worker``  multi-process worker schedule compilation
                    (``cluster/worker.compile_worker_schedule``) — one
                    probe per (window, slot) in row-major order; kinds
                    ``kill`` (the worker SIGKILLs itself mid-window)
                    and ``straggle`` (interference compute at the
                    window boundary, delivery skipped while busy)
  ``cluster:rpc``   the cluster transport's framed send/recv seams
                    (``cluster/transport.py``) — ``oserror`` models a
                    torn connection, ``hang`` a network partition the
                    recv deadline / heartbeat timeout must observe
  ``cluster:coordinator``  coordinator crash schedule compilation
                    (``cluster/coordinator.compile_coordinator_
                    schedule``) — one probe per window; ``kill`` = the
                    coordinator SIGKILLs itself at that window's
                    commit point (mid-window: pushes in RAM, commit
                    not yet WAL'd), ``hang`` = it freezes ``arg``
                    seconds there
  ``cluster:wal``   the coordinator's write-ahead-ledger append
                    (``cluster/wal.py``) — ``corrupt`` REALLY flips
                    record bytes (replay's CRC truncates the tail
                    with a quarantine), ``oserror``/``hang`` model
                    transient disk faults
  ``cluster:ps``    PS-shard crash schedule compilation
                    (``cluster/rowstore.compile_point_schedule``) —
                    one probe per window; ``kill`` = the shard dies at
                    the merge seam AFTER the commit record is durable
                    but BEFORE the merge applies (the WAL's REDO path:
                    recovery re-applies the logged row deltas),
                    ``hang`` = a slow shard merge
  ``cluster:replica``  the serving replica's per-score-frame seam
                    (``cluster/serve.py``) — ``kill`` = the replica
                    SIGKILLs itself mid-burst (thread mode slams its
                    sockets for the same router-side EOF observable),
                    ``hang`` = a frozen replica the router's
                    heartbeat timeout must detect and route around

  ``ckpt:write``    ``utils/checkpoint.save`` — the bytes about to land
                    on disk (``corrupt`` really flips file bytes; the
                    CRC footer catches it on restore)
  ``ckpt:read``     ``utils/checkpoint.restore`` — the bytes just read
  ``cache:write``   ``data/cache.build_cache`` — the packed-cache
                    generation + publish sequence
  ``data:gather``   ``ShardedDataset.gather`` — the host block gather
                    (runs on the prefetch producer thread when
                    streaming, so ``kill`` here dies silently and
                    exercises the consumer's liveness guard)
  ``data:h2d``      ``ShardedDataset.put`` — the host→device staging
  ``backend:init``  ``telemetry.supervisor.init_backend`` — each init
                    attempt (inside the deadline-guarded worker)
  ``segment:run``   ``utils/checkpoint.run_segmented`` — before each
                    compiled training segment

Fault kinds:

  ``oserror``   raise :class:`InjectedOSError` (a transient disk/net
                fault — the supervised-retry and restart paths recover)
  ``hang``      sleep ``arg`` seconds (default 0.05) then proceed — a
                stall that deadline guards (supervisor timeout,
                heartbeat, ``Prefetcher.get`` bounded wait) must
                observe, not a permanent wedge
  ``corrupt``   with a ``payload``: flip ``arg`` (default 8) bytes at
                seed-deterministic positions and return the corrupted
                copy (the torn-write model — checksums downstream must
                detect it); without a payload: raise
                :class:`InjectedCorruptionError` (checksum-detected
                corruption in flight, recovered like a transient fault)
  ``kill``      raise :class:`InjectedKill` — "the thread doing this
                work died". ``Prefetcher``'s producer catches it and
                dies WITHOUT posting (the silent-death failure mode its
                consumer guard exists for); everywhere else it
                propagates as a restartable ``RuntimeError``.
  ``straggle``  a SCHEDULING kind (``shard:straggle`` only): the
                matched (tick, shard) cell spends the tick on ``arg``
                units of injected interference compute instead of a
                logical training step. Consumed via :func:`probe` by
                the SSP schedule compiler — it never raises; the
                straggle cost is paid inside the compiled program.
  ``leave``     a SCHEDULING kind (``shard:leave`` only): the matched
                (boundary, shard) cell leaves the active membership for
                ``arg`` windows (default 2) and rejoins after. Consumed
                via :func:`probe` by the membership epoch compiler.

Plan spec (CLI ``--fault-plan`` / env ``$TDA_FAULT_PLAN``) — either a
path to a JSON file (``{"seed": 42, "rules": [{"point": ..., "hit":
2|"*", "prob": 0.1, "kind": ..., "arg": ...}]}``) or an inline string::

    seed=42;ckpt:write@1=oserror;segment:run@*=hang:0.1;data:gather@p0.2=kill

``point@N=kind`` fires on the N-th invocation (0-based) of the point;
``@*`` fires on every invocation; ``@pP`` fires with probability P from
a per-point RNG seeded by (seed, point) — deterministic given the
plan and the invocation sequence. First matching rule wins.

Like telemetry, the registry is process-global and free when disabled:
:func:`inject` is one global read on the clean path. Everything is
stdlib-only so cache builds and checkpoint writes in plain host
processes can run under chaos too.
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import threading
import time
import zlib

from tpu_distalg.telemetry import events as tevents

ENV_PLAN = "TDA_FAULT_PLAN"

POINTS = (
    "ckpt:write",
    "ckpt:read",
    "cache:write",
    "data:gather",
    "data:h2d",
    "backend:init",
    "segment:run",
    "shard:straggle",
    "shard:leave",
    "cluster:worker",
    "cluster:rpc",
    "cluster:coordinator",
    "cluster:wal",
    "cluster:replica",
    "cluster:ps",
)

KINDS = ("oserror", "hang", "corrupt", "kill", "straggle", "leave")

#: the SCHEDULING kinds: they fire at schedule-compilation seams via
#: :func:`probe` (which returns the rule instead of raising) — the
#: fault itself plays out inside the compiled SSP/cluster program,
#: bitwise-replayable because the schedule is a pure function of the
#: plan. A kind may be consumable at several points (``straggle`` is
#: both the in-process SSP schedule's and the cluster worker
#: schedule's interference kind).
_SCHEDULING_KINDS = {"straggle": ("shard:straggle", "cluster:worker"),
                     "leave": ("shard:leave",)}

#: points that take ONLY a restricted kind set (schedule-compilation
#: points take scheduling kinds; the cluster worker point also takes
#: ``kill`` — probed, then acted out by the worker itself as a real
#: SIGKILL; the rpc seam takes the transient transport kinds)
_POINT_KINDS = {
    "shard:straggle": ("straggle",),
    "shard:leave": ("leave",),
    "cluster:worker": ("straggle", "kill"),
    "cluster:rpc": ("oserror", "hang"),
    # the coordinator's own schedule: probed once per window by
    # cluster/coordinator.compile_coordinator_schedule — kill = a real
    # SIGKILL (thread mode slams every socket) at the window's commit
    # point, hang = a frozen coordinator the workers' reconnect/
    # deadline machinery must ride out
    "cluster:coordinator": ("kill", "hang"),
    # the WAL append seam (cluster/wal.py): corrupt flips record bytes
    # (the replay CRC quarantines the tail), oserror a transient disk
    # fault, hang a slow fsync
    "cluster:wal": ("oserror", "hang", "corrupt"),
    # the serving replica's score seam (cluster/serve.py): kill = a
    # real SIGKILL mid-burst (thread mode slams the replica's sockets
    # so the router sees the same EOF), hang = a frozen replica
    "cluster:replica": ("kill", "hang"),
    # the PS shard's merge seam (schedule-compiled, one probe per
    # window): kill = the shard dies AFTER the commit record is
    # durable but BEFORE the merge applies — the redo half of the WAL
    # contract (the coordinator point covers the rollback half);
    # hang = a slow shard the commit path rides out
    "cluster:ps": ("kill", "hang"),
}

DEFAULT_HANG_SECONDS = 0.05
DEFAULT_CORRUPT_BYTES = 8
DEFAULT_STRAGGLE_UNITS = 200
DEFAULT_LEAVE_WINDOWS = 2


class InjectedOSError(OSError):
    """A scheduled transient I/O fault (disk hiccup, flaky NFS, torn
    tunnel) — retryable by construction."""


class InjectedCorruptionError(InjectedOSError):
    """Scheduled in-flight corruption DETECTED at the seam (the checksum
    caught it) — recovered like any transient I/O fault. Undetected
    corruption is modeled separately: ``corrupt`` with a payload returns
    silently-flipped bytes and relies on a downstream CRC."""


class InjectedKill(RuntimeError):
    """The thread executing this work was killed. ``Prefetcher``'s
    producer dies silently on it (no error posted — the consumer's
    liveness guard must notice); in synchronous code it propagates as a
    restartable error."""


@dataclasses.dataclass(frozen=True)
class FaultRule:
    """One schedule entry: fire ``kind`` at ``point`` when the
    invocation index matches ``hit`` (``None`` = every invocation) or,
    when ``prob`` is set, with that per-invocation probability from the
    point's seeded RNG."""

    point: str
    kind: str
    hit: int | None = None
    prob: float | None = None
    arg: float | None = None

    def __post_init__(self):
        if self.point not in POINTS:
            raise ValueError(
                f"unknown injection point {self.point!r}; valid points: "
                f"{', '.join(POINTS)}")
        if self.kind not in KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r}; valid kinds: "
                f"{', '.join(KINDS)}")
        if self.prob is not None and not 0.0 < self.prob <= 1.0:
            raise ValueError(
                f"fault probability must be in (0, 1], got {self.prob}")
        if self.hit is not None and self.hit < 0:
            raise ValueError(f"fault hit index must be >= 0, got {self.hit}")
        want_points = _SCHEDULING_KINDS.get(self.kind)
        if want_points is not None and self.point not in want_points:
            raise ValueError(
                f"scheduling kind {self.kind!r} fires at "
                f"{' / '.join(map(repr, want_points))} only "
                f"(got {self.point!r})")
        allowed = _POINT_KINDS.get(self.point)
        if allowed is not None and self.kind not in allowed:
            sched = all(k in _SCHEDULING_KINDS for k in allowed)
            raise ValueError(
                f"point {self.point!r} takes "
                f"{'scheduling ' if sched else ''}kinds only "
                f"({', '.join(allowed)}), got {self.kind!r}")

    def spec(self) -> str:
        where = (f"p{self.prob}" if self.prob is not None
                 else "*" if self.hit is None else str(self.hit))
        arg = f":{self.arg}" if self.arg is not None else ""
        return f"{self.point}@{where}={self.kind}{arg}"


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """A seed plus an ordered rule schedule — the whole chaos input."""

    seed: int = 0
    rules: tuple[FaultRule, ...] = ()

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        """Parse an inline ``seed=..;point@hit=kind[:arg];..`` spec or a
        JSON plan file path (detected by existence / ``.json`` suffix)."""
        spec = spec.strip()
        if spec.endswith(".json") or os.path.isfile(spec):
            with open(spec) as f:
                doc = json.load(f)
            rules = []
            for r in doc.get("rules", []):
                hit = r.get("hit")
                rules.append(FaultRule(
                    point=r["point"], kind=r["kind"],
                    hit=None if hit in (None, "*") else int(hit),
                    prob=(None if r.get("prob") is None
                          else float(r["prob"])),
                    arg=(None if r.get("arg") is None
                         else float(r["arg"]))))
            return cls(seed=int(doc.get("seed", 0)), rules=tuple(rules))
        seed = 0
        rules = []
        for term in (t.strip() for t in spec.split(";") if t.strip()):
            if term.startswith("seed="):
                seed = int(term[len("seed="):])
                continue
            try:
                where_part, kind_part = term.split("=", 1)
                point, where = where_part.rsplit("@", 1)
            except ValueError:
                raise ValueError(
                    f"bad fault-plan term {term!r}: want "
                    f"'point@hit=kind[:arg]' (hit = N, '*', or 'pP') "
                    f"or 'seed=N'") from None
            kind, _, arg = kind_part.partition(":")
            rules.append(FaultRule(
                point=point, kind=kind,
                hit=(None if where in ("*",) or where.startswith("p")
                     else int(where)),
                prob=(float(where[1:]) if where.startswith("p")
                      else None),
                arg=float(arg) if arg else None))
        return cls(seed=seed, rules=tuple(rules))

    def spec(self) -> str:
        """The canonical inline spelling (parse/spec round-trips)."""
        return ";".join([f"seed={self.seed}"]
                        + [r.spec() for r in self.rules])


def _point_seed(seed: int, point: str, hit: int | None = None) -> int:
    tag = point if hit is None else f"{point}#{hit}"
    return (seed << 20) ^ zlib.crc32(tag.encode())


class FaultRegistry:
    """The live injector for one :class:`FaultPlan`: per-point
    invocation counters, per-point seeded RNGs (probability rules), and
    the record of every fault fired (``fired`` — what the chaos suite
    and the replay-determinism check compare)."""

    def __init__(self, plan: FaultPlan, *, sleep=time.sleep,
                 quiet: bool = False):
        self.plan = plan
        self._sleep = sleep
        self._quiet = quiet  # no telemetry: the plan-pure scratch
        #                      registries the SSP schedule compilers
        #                      probe (fires reach telemetry exactly
        #                      once, via the live ledger's record())
        self._lock = threading.Lock()
        self._hits: dict[str, int] = {}
        self._rngs: dict[str, random.Random] = {}
        self.fired: list[tuple[str, int, str]] = []

    def _match(self, point: str, hit: int) -> FaultRule | None:
        """First matching rule for this invocation. Probability rules
        consume one RNG draw per invocation of their point whether or
        not they fire — the property that keeps a prob-rule schedule
        deterministic in the invocation sequence."""
        chosen = None
        for rule in self.plan.rules:
            if rule.point != point:
                continue
            if rule.prob is not None:
                rng = self._rngs.setdefault(point, random.Random(
                    _point_seed(self.plan.seed, point)))
                fires = rng.random() < rule.prob
            else:
                fires = rule.hit is None or rule.hit == hit
            if fires and chosen is None:
                chosen = rule
        return chosen

    def _consume(self, point: str):
        """One invocation of ``point``: bump the counter, match, record
        and emit. Returns ``(rule | None, hit)`` — shared by
        :meth:`inject` (acts the fault out) and :meth:`probe` (returns
        the schedule entry)."""
        if point not in POINTS:
            raise ValueError(
                f"unknown injection point {point!r}; valid points: "
                f"{', '.join(POINTS)}")
        with self._lock:
            hit = self._hits.get(point, 0)
            self._hits[point] = hit + 1
            rule = self._match(point, hit)
            if rule is not None:
                self.fired.append((point, hit, rule.kind))
        if rule is not None and not self._quiet:
            tevents.emit("fault_injected", point=point, hit=hit,
                         kind=rule.kind, arg=rule.arg)
            tevents.counter("faults.injected")
            tevents.counter(f"faults.{rule.kind}")
        return rule, hit

    def probe(self, point: str):
        """Schedule-compilation seam: consume one invocation of
        ``point`` and return ``(kind, arg)`` when a rule fires, else
        ``None`` — no exception, no stall. The SSP straggle/membership
        compilers call this once per (tick, shard) cell in fixed order,
        so the same plan always compiles the same schedule (the
        property the bitwise-replay acceptance rests on)."""
        rule, _ = self._consume(point)
        if rule is None:
            return None
        return rule.kind, rule.arg

    def inject(self, point: str, payload=None):
        """The one call every injection point makes. Returns ``payload``
        (possibly corrupted); may raise or stall per the plan."""
        rule, hit = self._consume(point)
        if rule is None:
            return payload
        if rule.kind in _SCHEDULING_KINDS:
            # scheduling kinds act inside the compiled SSP program, not
            # at an I/O seam — an inject() here records the fire (the
            # replay ledger stays complete) and passes through
            return payload
        if rule.kind == "oserror":
            raise InjectedOSError(
                f"[fault] injected transient OSError at {point}#{hit}")
        if rule.kind == "hang":
            self._sleep(rule.arg if rule.arg is not None
                        else DEFAULT_HANG_SECONDS)
            return payload
        if rule.kind == "kill":
            raise InjectedKill(
                f"[fault] injected thread death at {point}#{hit}")
        # corrupt
        if payload is None:
            raise InjectedCorruptionError(
                f"[fault] injected corruption detected in flight at "
                f"{point}#{hit}")
        return self._corrupt(point, hit, payload,
                             n_bytes=int(rule.arg or DEFAULT_CORRUPT_BYTES))

    def _corrupt(self, point: str, hit: int, payload, *, n_bytes: int):
        """Flip ``n_bytes`` bytes of ``payload`` at seed-deterministic
        positions — the same plan corrupts the same bits every replay."""
        buf = bytearray(payload)
        if not buf:
            return bytes(buf)
        rng = random.Random(_point_seed(self.plan.seed, point, hit))
        for _ in range(max(1, n_bytes)):
            buf[rng.randrange(len(buf))] ^= 0xFF
        return bytes(buf)

    def record(self, fires) -> list:
        """Mirror externally-observed fires into this registry's
        ledger — the SSP schedule compilers probe a FRESH plan-pure
        QUIET registry (so restarts recompile identically without
        re-emitting), and the fires reach the chaos verdict and the
        telemetry JSONL exactly once here: a (point, hit, kind) triple
        already in the ledger (a restart's recompilation of the same
        schedule) is skipped. Returns the newly recorded fires."""
        with self._lock:
            seen = set(self.fired)
            new = [f for f in fires if f not in seen]
            self.fired.extend(new)
        for point, hit, kind in new:
            tevents.emit("fault_injected", point=point, hit=hit,
                         kind=kind, arg=None)
            tevents.counter("faults.injected")
            tevents.counter(f"faults.{kind}")
        return new

    def hits(self, point: str) -> int:
        with self._lock:
            return self._hits.get(point, 0)

    def summary(self) -> dict:
        with self._lock:
            return {"plan": self.plan.spec(),
                    "hits": dict(self._hits),
                    "fired": [{"point": p, "hit": h, "kind": k}
                              for p, h, k in self.fired]}


# ---- the process-global registry (telemetry-style lifecycle) ----------

_LOCK = threading.Lock()
_REGISTRY: FaultRegistry | None = None


def configure(spec: str | FaultPlan | None | bool = None,
              *, sleep=time.sleep) -> FaultRegistry | None:
    """Select the process-global registry. ``spec=None`` falls back to
    ``$TDA_FAULT_PLAN``; unset/empty disables injection (the default).
    ``spec=False`` force-disables, ignoring the env var. Each configure
    starts a FRESH registry (invocation counters at zero), so two runs
    under the same plan replay the identical fault sequence."""
    global _REGISTRY
    if spec is False:
        plan = None
    elif isinstance(spec, FaultPlan):
        plan = spec
    else:
        raw = spec or os.environ.get(ENV_PLAN) or None
        plan = FaultPlan.parse(raw) if raw else None
    with _LOCK:
        _REGISTRY = FaultRegistry(plan, sleep=sleep) if plan else None
        return _REGISTRY


def active() -> FaultRegistry | None:
    return _REGISTRY


def enabled() -> bool:
    return _REGISTRY is not None


def inject(point: str, payload=None):
    """Module-level injection point — a single global read when no plan
    is configured (the always-on cost at every I/O seam)."""
    reg = _REGISTRY
    if reg is None:
        return payload
    return reg.inject(point, payload)


def probe(point: str):
    """Module-level schedule probe (see :meth:`FaultRegistry.probe`):
    ``(kind, arg)`` when a rule fires on this invocation, else ``None``
    — and always ``None`` with no plan configured, so an unfaulted SSP
    run compiles empty straggle/membership schedules."""
    reg = _REGISTRY
    if reg is None:
        return None
    return reg.probe(point)
