"""Preemption-tolerant execution — SIGTERM is a request, not a death.

Production schedulers (spot/preemptible VMs, k8s eviction, slurm
requeue) deliver SIGTERM with a grace window; a run that dies mid-
segment wastes the whole segment and — before the CRC footer — risked a
torn checkpoint. :func:`install` turns the signal into a cooperative
request: the handler only sets a flag (async-signal-safe — no locks, no
I/O, nothing that could deadlock against a lock the interrupted main
thread holds), and ``run_segmented`` checks the flag at every segment
boundary AFTER the checkpoint for that segment is durably saved, then
raises :class:`Preempted`. The process exits with the distinct
:data:`PREEMPTED_RC` so supervisors (and the chaos suite) can tell
"preempted cleanly, resume me" from a crash — and the resumed run is
bitwise-identical to an uninterrupted one, because that is the
segmented-resume contract.

SIGINT gets the same grace path (Ctrl-C on an interactive run finishes
the segment and checkpoints instead of losing it), but a SECOND SIGINT
raises ``KeyboardInterrupt`` immediately — impatience must still work.
"""

from __future__ import annotations

import signal
import threading

# sysexits.h EX_TEMPFAIL: "temporary failure, retry later" — exactly the
# contract: re-run the same command and it resumes from the boundary
# checkpoint the preempted run saved.
PREEMPTED_RC = 75


class Preempted(SystemExit):
    """Raised at the first segment boundary after a preemption request.

    A ``SystemExit`` subclass on purpose: ``run_with_restarts`` never
    catches ``SystemExit`` (a preemption must not burn the restart
    budget re-running a healthy job), and an uncaught ``Preempted``
    already exits the interpreter with :data:`PREEMPTED_RC`."""

    def __init__(self, step: int | None = None):
        super().__init__(PREEMPTED_RC)
        self.step = step


_REQUESTED = threading.Event()
_SIGNALS_SEEN: list[int] = []
_INSTALLED = False


def _handler(signum, frame):
    del frame
    if signum == signal.SIGINT and _REQUESTED.is_set():
        raise KeyboardInterrupt
    # flag-set only: this runs between two arbitrary bytecodes of the
    # main thread — taking the telemetry sink lock here could deadlock
    # against the very write it interrupted. The boundary check emits
    # the event instead.
    _SIGNALS_SEEN.append(int(signum))
    _REQUESTED.set()


def install(signals=(signal.SIGTERM, signal.SIGINT)) -> bool:
    """Install the graceful handlers (main thread only — returns False
    when called anywhere else, e.g. under a threaded test runner)."""
    global _INSTALLED
    try:
        for s in signals:
            signal.signal(s, _handler)
    except ValueError:  # not the main thread
        return False
    _INSTALLED = True
    return True


def installed() -> bool:
    return _INSTALLED


def requested() -> bool:
    """True once a preemption signal has arrived (checked by
    ``run_segmented`` at each segment boundary, after the save)."""
    return _REQUESTED.is_set()


def request() -> None:
    """Programmatic preemption (tests; in-process schedulers)."""
    _REQUESTED.set()


def signals_seen() -> tuple[int, ...]:
    return tuple(_SIGNALS_SEEN)


def reset() -> None:
    """Clear the request flag + signal record (tests)."""
    _REQUESTED.clear()
    _SIGNALS_SEEN.clear()
