"""Deterministic fault injection & preemption tolerance.

The subsystem that keeps every recovery path in this repo a TESTED code
path instead of a claimed one: a seeded, replayable fault-injection
registry wired at every I/O and supervision seam (:mod:`registry`), a
cooperative SIGTERM/SIGINT preemption handler that checkpoints at the
next segment boundary and exits with a distinct rc (:mod:`preempt`),
and the chaos harness that runs real workloads under injected fault
schedules and asserts bitwise-equal recovery (:mod:`chaos`,
``tda chaos``).

Import cost is stdlib-only (plus the stdlib-only telemetry events
module) so checkpoint writers and cache builders in plain host
processes run under chaos without a jax import.
"""

from tpu_distalg.faults import preempt, registry
from tpu_distalg.faults.preempt import PREEMPTED_RC, Preempted
from tpu_distalg.faults.registry import (
    KINDS,
    POINTS,
    FaultPlan,
    FaultRegistry,
    FaultRule,
    InjectedCorruptionError,
    InjectedKill,
    InjectedOSError,
    active,
    configure,
    enabled,
    inject,
    probe,
)

__all__ = [
    "FaultPlan",
    "FaultRegistry",
    "FaultRule",
    "InjectedCorruptionError",
    "InjectedKill",
    "InjectedOSError",
    "KINDS",
    "POINTS",
    "PREEMPTED_RC",
    "Preempted",
    "active",
    "configure",
    "enabled",
    "inject",
    "preempt",
    "probe",
    "registry",
]
