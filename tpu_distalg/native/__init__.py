"""ctypes bindings for the native (C++) ingest runtime.

Loads ``libtda_ingest.so`` (built by ``native/Makefile`` into this package
directory, or auto-built on first use when a compiler is present). Every
entry point has a NumPy fallback, so the framework works without the
native library — just slower at 10M+ edge scale.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

_LIB_NAME = "libtda_ingest.so"
_here = os.path.dirname(__file__)
_lib = None
_load_attempted = False
#: symbols added after the first shipped .so — a prebuilt library may
#: predate them. load() tries ONE rebuild when any is missing; entry
#: points whose symbol still is not there fall back to NumPy (a stale
#: binary must degrade per-capability, never crash the import or the
#: caller).
_OPTIONAL_SYMBOLS = ("tda_pack_edge_rows",)
_missing_symbols: frozenset = frozenset()


def _build() -> bool:
    src_dir = os.path.join(_here, os.pardir, os.pardir, "native")
    makefile = os.path.join(src_dir, "Makefile")
    if not os.path.exists(makefile):
        return False
    try:
        subprocess.run(
            ["make", "-C", src_dir], check=True, capture_output=True,
            timeout=120,
        )
        return True
    except (subprocess.SubprocessError, OSError):
        return False


def _open_lib(path: str) -> ctypes.CDLL | None:
    try:
        return ctypes.CDLL(path)
    except OSError:
        return None


def load() -> ctypes.CDLL | None:
    """The loaded library, building it on first use if needed; None when
    unavailable (callers fall back to NumPy).

    Capability handling for stale binaries: a prebuilt ``.so`` that
    predates :data:`_OPTIONAL_SYMBOLS` triggers ONE rebuild attempt
    (same build-if-missing path); if the rebuild cannot run (no
    compiler, read-only checkout) the library still loads with the
    missing entry points recorded in :data:`_missing_symbols` — their
    Python wrappers fall back to NumPy instead of raising
    ``AttributeError`` mid-ingest."""
    global _lib, _load_attempted, _missing_symbols
    if _lib is not None or _load_attempted:
        return _lib
    _load_attempted = True
    path = os.path.join(_here, _LIB_NAME)
    if not os.path.exists(path) and not _build():
        return None
    lib = _open_lib(path)
    if lib is None:
        return None
    stale = [s for s in _OPTIONAL_SYMBOLS if not hasattr(lib, s)]
    if stale and _build():
        # a fresh build carries every symbol this binding knows about;
        # reopen so the new ones resolve (dlopen caches per path, but
        # the handle we already hold keeps the OLD mapping alive)
        rebuilt = _open_lib(path)
        if rebuilt is not None:
            lib = rebuilt
            stale = [s for s in _OPTIONAL_SYMBOLS if not hasattr(lib, s)]
    _missing_symbols = frozenset(stale)
    i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
    i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
    f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
    lib.tda_dedupe_edges.argtypes = [i64p, i64p, ctypes.c_int64]
    lib.tda_dedupe_edges.restype = ctypes.c_int64
    lib.tda_out_degree.argtypes = [i64p, ctypes.c_int64, i32p,
                                   ctypes.c_int64]
    lib.tda_out_degree.restype = None
    lib.tda_csr_offsets.argtypes = [i64p, ctypes.c_int64, i64p,
                                    ctypes.c_int64]
    lib.tda_csr_offsets.restype = None
    lib.tda_parse_edges_text.argtypes = [ctypes.c_char_p, i64p, i64p,
                                         ctypes.c_int64]
    lib.tda_parse_edges_text.restype = ctypes.c_int64
    lib.tda_counting_sort_perm.argtypes = [i64p, ctypes.c_int64,
                                           ctypes.c_int64, i64p]
    lib.tda_counting_sort_perm.restype = ctypes.c_int32
    if "tda_pack_edge_rows" not in _missing_symbols:
        lib.tda_pack_edge_rows.argtypes = [i64p, i64p, f32p,
                                           ctypes.c_int64, i32p]
        lib.tda_pack_edge_rows.restype = None
    _lib = lib
    return _lib


def available() -> bool:
    return load() is not None


def has_symbol(name: str) -> bool:
    """Whether the loaded library exports ``name`` — False when the
    library is absent OR it loaded as a stale build missing the symbol
    (the per-capability skip the graph ingest keys its fallback on)."""
    return load() is not None and name not in _missing_symbols


def pack_edge_rows(src: np.ndarray, dst: np.ndarray,
                   w: np.ndarray) -> np.ndarray:
    """Interleave dst-sorted edge columns into packed ``(E, 3)`` int32
    cache rows ``[src, dst, bits(w)]`` — the ``csr_edge_blocks_i32``
    layout (``tpu_distalg/graphs/ingest.py``). Native path and NumPy
    fallback are byte-identical (int32 truncation of in-range ids +
    the f32 bit pattern), so a cache is deterministic in its header
    whichever path built it."""
    src = np.ascontiguousarray(src, dtype=np.int64)
    dst = np.ascontiguousarray(dst, dtype=np.int64)
    w = np.ascontiguousarray(w, dtype=np.float32)
    n = len(src)
    out = np.empty((n, 3), dtype=np.int32)
    if n and has_symbol("tda_pack_edge_rows"):
        load().tda_pack_edge_rows(src, dst, w, n, out)
        return out
    out[:, 0] = src.astype(np.int32)
    out[:, 1] = dst.astype(np.int32)
    out[:, 2] = w.view(np.int32)
    return out


def dedupe_edges_pair(edges: np.ndarray):
    """Sorted, deduplicated (src, dst) contiguous column pair from an
    (E, 2) int edge array — the zero-extra-copy native interface.

    Native path: pack-sort-unique in C++; fallback: ``np.unique(axis=0)``.
    Matches ``links.distinct()`` set semantics (reference pagerank.py:41).
    """
    edges = np.ascontiguousarray(edges, dtype=np.int64)
    lib = load()
    if lib is None or len(edges) == 0:
        uniq = np.unique(edges, axis=0)
        return np.ascontiguousarray(uniq[:, 0]), np.ascontiguousarray(
            uniq[:, 1]
        )
    src = np.ascontiguousarray(edges[:, 0])
    dst = np.ascontiguousarray(edges[:, 1])
    m = lib.tda_dedupe_edges(src, dst, len(src))
    return src[:m], dst[:m]


def dedupe_edges(edges: np.ndarray) -> np.ndarray:
    """(E', 2) stacked variant of ``dedupe_edges_pair``."""
    src, dst = dedupe_edges_pair(edges)
    return np.stack([src, dst], axis=1)


def out_degree(src: np.ndarray, n_vertices: int) -> np.ndarray:
    src = np.ascontiguousarray(src, dtype=np.int64)
    if len(src) and (m := int(src.max())) >= n_vertices:
        # the C++ histogram writes degree[src[i]] unchecked — reject
        # out-of-range ids here rather than corrupt memory
        raise ValueError(
            f"src id {m} out of range for n_vertices={n_vertices}"
        )
    lib = load()
    if lib is None:
        return np.bincount(src, minlength=n_vertices).astype(np.int32)
    deg = np.zeros((n_vertices,), dtype=np.int32)
    lib.tda_out_degree(src, len(src), deg, n_vertices)
    return deg


def csr_offsets(sorted_src: np.ndarray, n_vertices: int) -> np.ndarray:
    """Row-offset array (n_vertices+1,) for edges sorted by src."""
    sorted_src = np.ascontiguousarray(sorted_src, dtype=np.int64)
    lib = load()
    if lib is None:
        counts = np.bincount(sorted_src, minlength=n_vertices)
        out = np.zeros((n_vertices + 1,), dtype=np.int64)
        np.cumsum(counts, out=out[1:])
        return out
    out = np.zeros((n_vertices + 1,), dtype=np.int64)
    lib.tda_csr_offsets(sorted_src, len(sorted_src), out, n_vertices)
    return out


def counting_sort_perm(keys: np.ndarray, key_range: int) -> np.ndarray:
    """Stable argsort of bounded integer keys — O(n + range) counting
    sort in C++ (NumPy fallback: ``np.argsort(kind='stable')``). The
    host-prep behind PageRank's dst-sorted edge layout."""
    keys = np.ascontiguousarray(keys, dtype=np.int64)
    lib = load()
    if lib is None or len(keys) == 0:
        # fallback validates too, so environments without a compiler
        # reject corrupt ids exactly like the native path's range check
        if len(keys) and (keys.min() < 0 or keys.max() >= key_range):
            raise ValueError(
                f"counting_sort_perm: key out of range [0, {key_range})"
            )
        return np.argsort(keys, kind="stable")
    perm = np.empty((len(keys),), dtype=np.int64)
    if lib.tda_counting_sort_perm(keys, len(keys), key_range, perm):
        raise ValueError(
            f"counting_sort_perm: key out of range [0, {key_range})"
        )
    return perm


def parse_edges_text(path: str, capacity: int) -> np.ndarray:
    """Parse a '#'-commented whitespace edge-list file into (E, 2) int64."""
    lib = load()
    if lib is None:
        return np.loadtxt(path, dtype=np.int64, comments="#").reshape(-1, 2)
    src = np.empty((capacity,), dtype=np.int64)
    dst = np.empty((capacity,), dtype=np.int64)
    n = lib.tda_parse_edges_text(path.encode(), src, dst, capacity)
    if n == -1:
        raise FileNotFoundError(path)
    if n == -2:
        raise ValueError(f"edge file exceeds capacity {capacity}")
    return np.stack([src[:n], dst[:n]], axis=1)
