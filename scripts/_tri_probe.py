"""Probe: flat triangular grid for the causal self-block flash forward."""
import functools
import jax, jax.numpy as jnp, numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from tpu_distalg.utils import profiling, prng

S, H, d = 32768, 8, 128
BQ = BKV = 2048
NQ = S // BQ
N_LIVE = NQ * (NQ + 1) // 2
_NEG = -1e30

# i-major live-tile enumeration (j <= i)
i_map = np.concatenate([[i] * (i + 1) for i in range(NQ)]).astype(np.int32)
j_map = np.concatenate([np.arange(i + 1) for i in range(NQ)]).astype(np.int32)

def kernel(im_ref, jm_ref, bias_ref, q_ref, k_ref, v_ref, o_ref, m_ref,
           l_ref, oacc, macc, lacc, *, scale):
    t = pl.program_id(1)
    i = im_ref[t]
    j = jm_ref[t]

    @pl.when(j == 0)
    def _init():
        oacc[:] = jnp.zeros_like(oacc)
        macc[:] = jnp.full_like(macc, -jnp.inf)
        lacc[:] = jnp.zeros_like(lacc)

    # unconditional body: masking is ONE add of the index-map-selected
    # bias block (zeros for full tiles, triangular -1e30 on the diag);
    # every query row sees >= 1 real key in the self block, so m stays
    # finite and no guard is needed
    q = q_ref[0]
    k = k_ref[0]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    s = s + bias_ref[0]
    m_new = jnp.maximum(macc[:], jnp.max(s, axis=1, keepdims=True))
    alpha = jnp.exp(macc[:] - m_new)
    p = jnp.exp(s - m_new)
    lacc[:] = lacc[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
    oacc[:] = oacc[:] * alpha + jax.lax.dot_general(
        p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    macc[:] = m_new

    @pl.when(j == i)   # diagonal tile is the row's last
    def _store():
        o_ref[0] = oacc[:]
        m_ref[0] = macc[:]
        l_ref[0] = lacc[:]

@functools.partial(jax.jit, static_argnames=("scale",))
def tri_flash(q, k, v, *, scale):
    h = q.shape[0]
    qs = lambda hh, t, im, jm: (hh, im[t], 0)
    ks = lambda hh, t, im, jm: (hh, jm[t], 0)
    bs = lambda hh, t, im, jm: (jnp.where(jnp.equal(im[t], jm[t]), 1, 0), 0, 0)
    r = jax.lax.broadcasted_iota(jnp.int32, (BQ, BKV), 0)
    c = jax.lax.broadcasted_iota(jnp.int32, (BQ, BKV), 1)
    bias = jnp.stack([jnp.zeros((BQ, BKV), jnp.float32),
                      jnp.where(r >= c, 0.0, _NEG)])
    return pl.pallas_call(
        functools.partial(kernel, scale=scale),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(h, N_LIVE),
            in_specs=[pl.BlockSpec((1, BQ, BKV), bs),
                      pl.BlockSpec((1, BQ, d), qs),
                      pl.BlockSpec((1, BKV, d), ks),
                      pl.BlockSpec((1, BKV, d), ks)],
            out_specs=[pl.BlockSpec((1, BQ, d), qs),
                       pl.BlockSpec((1, BQ, 1), qs),
                       pl.BlockSpec((1, BQ, 1), qs)],
            scratch_shapes=[pltpu.VMEM((BQ, d), jnp.float32),
                            pltpu.VMEM((BQ, 1), jnp.float32),
                            pltpu.VMEM((BQ, 1), jnp.float32)],
        ),
        out_shape=[jax.ShapeDtypeStruct((h, S, d), jnp.float32),
                   jax.ShapeDtypeStruct((h, S, 1), jnp.float32),
                   jax.ShapeDtypeStruct((h, S, 1), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
            vmem_limit_bytes=100 * 1024 * 1024),
    )(jnp.asarray(i_map), jnp.asarray(j_map), bias, q, k, v)

key = prng.root_key(0)
qh, kh, vh = (jax.random.normal(jax.random.fold_in(key, i), (H, S, d),
                                jnp.bfloat16) for i in range(3))
scale = float(1.0 / np.sqrt(d))
o, m, l = tri_flash(qh, kh, vh, scale=scale)
out = np.asarray(o / l)

# correctness vs the production kernel
from tpu_distalg.ops.pallas_attention import flash_attention_block
o2, m2, l2 = flash_attention_block(
    qh, kh, vh, jnp.zeros((H, S, d), jnp.float32),
    jnp.full((H, S, 1), -jnp.inf, jnp.float32),
    jnp.zeros((H, S, 1), jnp.float32), 0, 0, scale=scale, causal=True)
np.testing.assert_allclose(out, np.asarray(o2 / l2), rtol=2e-4, atol=2e-4)
print("CORRECT")

best, _ = profiling.steps_per_sec(lambda: tri_flash(qh, kh, vh, scale=scale),
                                  steps=1, with_stats=True, repeats=3, chain=4)
flops = S * S / 2 * d * H * 2 * 2
print(f"tri grid: {flops*best/1e12:.1f} TFLOP/s causal fwd")
