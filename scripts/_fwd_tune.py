"""Forward flash block tuning at 32k."""
import functools
import jax, jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from tpu_distalg.parallel import DATA_AXIS, data_parallel, get_mesh
from tpu_distalg.parallel.ring import ring_attention
from tpu_distalg.utils import profiling, prng

mesh = get_mesh()
S, H, d = 32768, 8, 128
key = prng.root_key(0)
q, k, v = (jax.random.normal(jax.random.fold_in(key, i), (S, H, d), jnp.bfloat16)
           for i in range(3))
flops = S * S / 2 * d * H * 2 * 2
for bq, bkv in [(2048, 2048), (4096, 2048), (2048, 4096), (4096, 4096),
                (8192, 2048), (1024, 4096), (4096, 1024), (8192, 1024)]:
    try:
        f = jax.jit(data_parallel(
            functools.partial(ring_attention, causal=True, use_flash=True,
                              flash_block_q=bq, flash_block_kv=bkv),
            mesh, in_specs=(P(DATA_AXIS, None, None),) * 3,
            out_specs=P(DATA_AXIS, None, None)))
        best, _ = profiling.steps_per_sec(lambda: f(q, k, v), steps=1,
                                          with_stats=True, repeats=3, chain=4)
        print(f"bq={bq} bkv={bkv}: {flops*best/1e12:.1f} TFLOP/s fwd")
    except Exception as e:
        print(f"bq={bq} bkv={bkv}: FAILED {type(e).__name__}")
