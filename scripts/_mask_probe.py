import functools
import jax, jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from tpu_distalg.parallel import DATA_AXIS, data_parallel, get_mesh
from tpu_distalg.parallel.ring import ring_attention
from tpu_distalg.utils import profiling, prng

mesh = get_mesh()
S, H, d = 32768, 8, 128
key = prng.root_key(0)
q, k, v = (jax.random.normal(jax.random.fold_in(key, i), (S, H, d), jnp.bfloat16)
           for i in range(3))
for causal in (True, False):
    f = jax.jit(data_parallel(
        functools.partial(ring_attention, causal=causal, use_flash=True),
        mesh, in_specs=(P(DATA_AXIS, None, None),) * 3,
        out_specs=P(DATA_AXIS, None, None)))
    best, _ = profiling.steps_per_sec(lambda: f(q, k, v), steps=1,
                                      with_stats=True, repeats=3, chain=4)
    frac = 0.5 if causal else 1.0
    flops = S * S * frac * d * H * 2 * 2
    print(f"causal={causal}: {flops*best/1e12:.1f} TFLOP/s "
          f"({1e3/best:.1f} ms/call)")
