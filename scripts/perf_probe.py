"""Perf probe: compare SSGD step-path variants on the attached device.

Prints steps/sec for each (sampler, dtype, kernel) combination at bench
scale (same workload as bench.py: 1M rows, 125 features + bias → 128-wide
packed matrix) so we can pick the fastest faithful path for bench.py.
"""

import jax.numpy as jnp

from tpu_distalg.models import ssgd
from tpu_distalg.ops import logistic
from tpu_distalg.parallel import get_mesh, parallelize
from tpu_distalg.utils import datasets, prng, profiling

N_ROWS = 1 << 20
N_FEATURES = 125  # +bias = 126; packed layout pads to 128 (bench.py)
N_STEPS = 200


def _data():
    X, y = datasets.synthetic_two_class(N_ROWS, N_FEATURES, seed=0)
    return datasets.add_bias_column(X), y


def _time(run, w0):
    return profiling.steps_per_sec(run, w0, steps=N_STEPS)


def probe(name, config):
    mesh = get_mesh()
    X, y = _data()
    Xs = parallelize(X, mesh, dtype=jnp.dtype(config.x_dtype))
    ys = parallelize(y, mesh)
    w0 = logistic.init_weights(prng.root_key(7), X.shape[1])
    fn = ssgd.make_train_fn(mesh, config, Xs.n_padded)
    X_ev = jnp.zeros((1, X.shape[1]), jnp.float32)
    y_ev = jnp.zeros((1,), jnp.float32)
    best = _time(lambda w: fn(Xs.data, ys.data, Xs.mask, X_ev, y_ev, w)[0],
                 w0)
    print(f"{name:30s} {best:10.1f} steps/s", flush=True)


def probe_fused(name, config):
    """Fused-sampler probe via ssgd.prepare_fused (the bench.py path)."""
    mesh = get_mesh()
    if next(iter(mesh.devices.flat)).platform != "tpu":
        print(f"{name:30s}       skip (needs TPU)", flush=True)
        return
    X, y = _data()
    try:
        fn, X2, w0, meta = ssgd.prepare_fused(X, y, mesh, config)
    except ValueError as e:
        # e.g. fused_train on a multi-data-shard mesh
        print(f"{name:30s}       skip ({e})", flush=True)
        return
    dummy = jnp.zeros((1,), jnp.float32)
    ev = (jnp.zeros((1, meta["d_total"]), jnp.float32),
          jnp.zeros((1,), jnp.float32))
    best = _time(lambda w: fn(X2, dummy, dummy, ev[0], ev[1], w)[0], w0)
    print(f"{name:30s} {best:10.1f} steps/s", flush=True)


if __name__ == "__main__":
    C = ssgd.SSGDConfig
    probe("bernoulli f32", C(n_iterations=N_STEPS, eval_test=False))
    probe("bernoulli bf16",
          C(n_iterations=N_STEPS, eval_test=False, x_dtype="bfloat16"))
    probe("pallas f32",
          C(n_iterations=N_STEPS, eval_test=False, use_pallas=True))
    probe("fixed f32",
          C(n_iterations=N_STEPS, eval_test=False, sampler="fixed"))
    probe("fixed bf16",
          C(n_iterations=N_STEPS, eval_test=False, sampler="fixed",
            x_dtype="bfloat16"))
    probe_fused("fused bf16",
                C(n_iterations=N_STEPS, eval_test=False, sampler="fused",
                  x_dtype="bfloat16", init_seed=7))
    probe_fused("fused_gather bf16",
                C(n_iterations=N_STEPS, eval_test=False,
                  sampler="fused_gather", gather_block_rows=8192,
                  x_dtype="bfloat16", shuffle_seed=0, init_seed=7))
    probe_fused("fused_train bf16 (megakernel)",
                C(n_iterations=N_STEPS, eval_test=False,
                  sampler="fused_train", gather_block_rows=8192,
                  mega_steps=100, x_dtype="bfloat16", shuffle_seed=0,
                  init_seed=7))
