"""Perf probe: compare SSGD step-path variants on the attached device.

Prints steps/sec for each (sampler, dtype, kernel) combination at bench
scale so we can pick the fastest faithful path for bench.py.
"""

import time

import jax
import jax.numpy as jnp

from tpu_distalg.models import ssgd
from tpu_distalg.ops import logistic
from tpu_distalg.parallel import get_mesh, parallelize
from tpu_distalg.utils import datasets, prng

N_ROWS = 1 << 20
N_FEATURES = 128
N_STEPS = 200


def probe(name, config):
    mesh = get_mesh()
    X, y = datasets.synthetic_two_class(N_ROWS, N_FEATURES, seed=0)
    X = datasets.add_bias_column(X)
    Xs = parallelize(X, mesh, dtype=jnp.dtype(config.x_dtype))
    ys = parallelize(y, mesh)
    w0 = logistic.init_weights(prng.root_key(7), X.shape[1])
    fn = ssgd.make_train_fn(mesh, config, Xs.n_padded)
    X_ev = jnp.zeros((1, X.shape[1]), jnp.float32)
    y_ev = jnp.zeros((1,), jnp.float32)
    w, _ = fn(Xs.data, ys.data, Xs.mask, X_ev, y_ev, w0)
    jax.block_until_ready(w)
    best = 0.0
    for _ in range(3):
        t0 = time.perf_counter()
        w, _ = fn(Xs.data, ys.data, Xs.mask, X_ev, y_ev, w)
        jax.block_until_ready(w)
        best = max(best, N_STEPS / (time.perf_counter() - t0))
    print(f"{name:30s} {best:10.1f} steps/s", flush=True)


if __name__ == "__main__":
    C = ssgd.SSGDConfig
    probe("bernoulli f32", C(n_iterations=N_STEPS, eval_test=False))
    probe("bernoulli bf16",
          C(n_iterations=N_STEPS, eval_test=False, x_dtype="bfloat16"))
    probe("pallas f32",
          C(n_iterations=N_STEPS, eval_test=False, use_pallas=True))
    probe("fixed f32",
          C(n_iterations=N_STEPS, eval_test=False, sampler="fixed"))
    probe("fixed bf16",
          C(n_iterations=N_STEPS, eval_test=False, sampler="fixed",
            x_dtype="bfloat16"))
