#!/usr/bin/env python3
"""Reconcile README performance claims against the newest bench artifact.

VERDICT weak #2: README numbers can drift from what the recorded
``BENCH_r*.json`` artifacts actually measured. This script extracts the
README's headline performance numbers (a claims table of regexes — one
per metric the bench emits), loads the newest artifact whose ``parsed``
field carries metrics (``all_metrics`` map or a single metric line),
and FAILS (exit 1) when a claim's counterpart metric is present in the
artifact but outside tolerance in either direction.

A claim whose metric the artifact simply does not carry is a WARNING by
default (old artifacts recorded one line, not the summary map; nothing
to reconcile) and a failure under ``--strict``. No artifact with any
parsed metrics at all → warning + exit 0 (nothing recorded yet).

Tolerance default 0.35: README claims are best-of-repeats on a shared
chip whose session-to-session spread is recorded at ~10-15%; the check
is a drift tripwire, not a timing assertion.

Usage::

    python scripts/check_readme_claims.py [--readme README.md]
        [--artifact BENCH_rNN.json] [--tolerance 0.35] [--strict]

Stdlib only — runs anywhere, no jax.
"""

from __future__ import annotations

import argparse
import os
import re
import sys

# (metric key in the bench artifact, README regex capturing the claimed
# number, multiplier mapping the captured text to the metric's unit).
# Numbers may be written "24 155" (thousands spaces) — _num strips them.
CLAIMS = [
    ("ssgd_lr_steps_per_sec_per_chip",
     r"\*\*SSGD, 1M rows\*\*:\s*([\d\s]+?)\s*steps/s/chip", 1.0),
    ("ssgd_lr_fused_gather_steps_per_sec_per_chip",
     r"`fused_gather` sampler at the SAME\s+geometry records\s*"
     r"([\d\s]+?)\s*\(", 1.0),
    ("ssgd_lr_100m_rows_steps_per_sec_per_chip",
     r"\*\*SSGD, 100M rows\*\*:\s*([\d\s]+?)\s*steps/s", 1.0),
    ("ssgd_lr_1b_rows_virtual_steps_per_sec_per_chip",
     r"\*\*SSGD, 1B logical rows\*\*[^:]*:\s*([\d\s]+?)\s*steps/s", 1.0),
    ("ma_local_sgd_local_steps_per_sec_per_chip",
     r"\*\*MA/BMUF/EASGD\*\*.*?\(([\d\s]+?)\s*local steps/s/chip", 1.0),
    ("kmeans_10m_iters_per_sec_per_chip",
     r"\*\*k-means, 10M points\*\*:\s*([\d\s]+?)\s*iter/s", 1.0),
    ("pagerank_1m_iters_per_sec",
     r"\*\*PageRank, 1M vertices[^*]*\*\*:\s*\*\*([\d.\s]+?)\s*iter/s",
     1.0),
    # out-of-core graph engine (round 12): claimed as a floor ("+")
    # until the first real-backend round records the achieved rate —
    # the cpu-tagged fallback line cannot serve as the reference
    ("pagerank_100m_iters_per_sec",
     r"\*\*PageRank, 100M vertices[^*]*\*\*:\s*\*\*([\d.]+?)\+\s*"
     r"iter/s", 1.0),
    ("als_4kx16k_sweeps_per_sec_per_chip",
     r"\*\*ALS 4096×16384 rank-64\*\*:\s*([\d\s]+?)\s*sweeps/s", 1.0),
    ("als_4kx16k_noisy_ridge_sweeps_per_sec_per_chip",
     r"HARD\s+instance[^)]*?\)\s*runs\s*([\d\s]+?)\s*sweeps/s", 1.0),
    ("ring_attention_32k_tokens_per_sec_per_chip",
     r"32k-token forward\s+([\d.]+?)M tokens/s", 1e6),
    ("ring_attention_32k_fwd_bwd_tokens_per_sec_per_chip",
     r"32k forward\+backward\s+([\d.]+?)k tokens/s", 1e3),
    ("ring_attention_128k_tokens_per_sec_per_chip",
     r"128k-token forward\s+([\d.]+?)k tokens/s", 1e3),
    ("ring_attention_128k_fwd_bwd_tokens_per_sec_per_chip",
     r"128k forward\+backward\s+~?([\d.]+?)k tokens/s", 1e3),
    # comms-layer acceptance pair (PR 10): the wire-byte reduction the
    # compressed schedules achieve vs dense, as measured by the bench
    # comm phase / multichip dryrun (ssgd_comm_* lines)
    ("ssgd_comm_int8_wire_reduction_vs_dense",
     r"int8 moves\s+\*\*([\d.]+?)× fewer\*\*", 1.0),
    ("ssgd_comm_topk_wire_reduction_vs_dense",
     r"topk \*\*([\d.]+?)× fewer\*\*", 1.0),
    # round-11 measured-step-time pair: native int8 wire + overlap vs
    # dense at the comm-bound geometry (bench comm_speedup phase /
    # multichip dryrun), claimed as the >=1.0x acceptance form until a
    # multi-shard real-backend round records the achieved factor
    ("ssgd_comm_int8_step_speedup",
     r"int8 runs \*\*([\d.]+?)×\+\*\* the dense step rate", 1.0),
    ("ssgd_comm_topk_step_speedup",
     r"topk \*\*([\d.]+?)×\+\*\* the dense step rate", 1.0),
    # stale-synchronous pair (round 14): measured straggler speedup is
    # a floor, honest on host meshes too (the injected interference is
    # real compute and the BSP barrier really waits); the equal-loss
    # steps ratio is a CEILING (lower = converges like BSP)
    ("ssgd_ssp_straggler_speedup",
     r"SSP runs \*\*([\d.]+?)×\+\*\* the BSP step rate", 1.0),
    ("ssgd_ssp_equal_loss_steps",
     r"BSP-endpoint accuracy\s+within \*\*([\d.]+?)×\*\* the steps",
     1.0),
    # multi-process elastic runtime (round 16): the kill-one-worker
    # elastic-vs-restart wall-clock ratio is a FLOOR (host
    # processes/threads by construction — honest on every backend);
    # the PS push/pull round trip is a CEILING (lower is better)
    ("ssgd_cluster_elastic_speedup",
     r"kill-one-worker run \*\*([\d.]+?)×\+\*\* the BSP-restart "
     r"baseline", 1.0),
    ("cluster_push_pull_ms",
     r"push/pull round trip under \*\*([\d.]+?)\s*ms\*\*", 1.0),
    # coordinator crash tolerance (round 17): detect -> WAL replay ->
    # worker reconnect -> first recommitted window, a CEILING (lower
    # is better; recovery must stay invisible-fast)
    ("cluster_coordinator_recovery_ms",
     r"coordinator kill -9 recovers in under "
     r"\*\*([\d.]+?)\s*ms\*\*", 1.0),
    # compressed cluster wire (round 18): measured frame bytes, dense
    # vs --comm int8, a FLOOR (TCP is a real wire — honest on every
    # backend, unlike the host-shared-memory in-process comm lines)
    ("cluster_wire_reduction_vs_dense",
     r"`--comm int8` cluster wire moves \*\*([\d.]+?)×\+ fewer\*\*",
     1.0),
    # sharded-state parameter server (round 21): the fleet PageRank
    # iteration rate is a FLOOR (host numpy + real wire frames —
    # honest on every backend); the sparse-pull fraction is a CEILING
    # (lower = sparser = the bigger-than-one-host story working)
    ("pagerank_cluster_iters_per_sec",
     r"sharded row store\*\*:\s*\*\*([\d\s.]+?)\+\s*iter/s\*\*", 1.0),
    ("cluster_sparse_pull_fraction",
     r"sparse-pull fraction under\s+\*\*([\d.]+?)\*\*", 1.0),
    # online serving layer (round 13): throughput claimed as a floor
    # and the scoring p99 as a CEILING until the first real-backend
    # round records the achieved numbers (cpu-tagged fallback lines
    # cannot serve as the reference)
    ("serve_als_qps",
     r"\*\*ALS serving[^*]*\*\*:\s*\*\*([\d\s.]+?)\+\s*req/s", 1.0),
    ("serve_lr_p99_ms",
     r"LR scoring p99 under \*\*([\d.]+?)\s*ms\*\*", 1.0),
    # distributed serving plane (round 19): throughput and first-try
    # availability claimed as FLOORS, the client p99 under a replica
    # kill as a CEILING — the fleet is host threads/processes by
    # construction, so like the training cluster the numbers are
    # honest on every backend
    ("cluster_serve_qps",
     r"serving router\s+sustains \*\*([\d\s]+?)\+\s*req/s\*\*", 1.0),
    ("cluster_serve_p99_under_kill_ms",
     r"replica kill -9 mid-burst\s+keeps client p99 under "
     r"\*\*([\d.]+?)\s*ms\*\*", 1.0),
    ("cluster_serve_availability",
     r"first-try availability at \*\*([\d.]+?)\+\*\*", 1.0),
    # partition-engine round (round 15): all three claimed as FLOORS
    # until the first real-backend round records achieved numbers
    # (cpu-tagged fallback lines cannot serve as the reference)
    ("reshard_1gb_gbps",
     r"reshard sustains\s+\*\*([\d.]+?)\+\s*GB/s\*\*", 1.0),
    ("ssgd_2d_mesh_step_speedup",
     r"`--mesh-shape 2x2` runs \*\*([\d.]+?)×\+\*\* the 1-D", 1.0),
    ("closure_10m_paths_per_sec",
     r"closure at \*\*([\d\s]+?)\+\s*paths/s\*\*", 1.0),
    # platform-aware autotuner (round 22): both A/B ratios claimed as
    # FLOORS at the parity line — the resolver must never ship a
    # geometry slower than the default table (the step phase RAISES
    # on a sub-1.0 measurement rather than recording it; identical-
    # geometry rounds record exactly 1.0). Only artifacts whose rig
    # tag matches this machine reconcile: tuned geometry is per-rig
    # (bench_artifacts skips mismatched-rig rounds like cpu rounds)
    ("tuned_step_speedup",
     r"`--tune auto` runs \*\*([\d.]+?)×\+\*\* the default-table "
     r"step rate", 1.0),
    ("cluster_tuned_push_pull_speedup",
     r"tuned cluster geometry holds \*\*([\d.]+?)×\+\*\* the "
     r"default-table push/pull rate", 1.0),
]

#: claims stated as FLOORS ("×+"): the measured value may exceed the
#: claim by any margin (that is the feature working); only a measured
#: value tolerance-below the floor fails
FLOOR_CLAIMS = frozenset((
    "ssgd_comm_int8_step_speedup",
    "ssgd_comm_topk_step_speedup",
    "pagerank_100m_iters_per_sec",
    "serve_als_qps",
    "ssgd_ssp_straggler_speedup",
    "ssgd_cluster_elastic_speedup",
    "cluster_wire_reduction_vs_dense",
    "cluster_serve_qps",
    "cluster_serve_availability",
    "pagerank_cluster_iters_per_sec",
    "reshard_1gb_gbps",
    "ssgd_2d_mesh_step_speedup",
    "closure_10m_paths_per_sec",
    "tuned_step_speedup",
    "cluster_tuned_push_pull_speedup",
))

#: claims stated as CEILINGS ("under X ms" — latency metrics, lower is
#: better): a measured value below the claim is the feature working;
#: only a measured value tolerance-above the ceiling fails
CEILING_CLAIMS = frozenset((
    "serve_lr_p99_ms",
    "ssgd_ssp_equal_loss_steps",
    "cluster_push_pull_ms",
    "cluster_coordinator_recovery_ms",
    "cluster_serve_p99_under_kill_ms",
    "cluster_sparse_pull_fraction",
))


def _num(text: str) -> float:
    return float(re.sub(r"\s", "", text))


def extract_claims(readme_text: str) -> dict[str, float]:
    """{metric: claimed value} for every claim regex that matches."""
    out = {}
    for metric, pattern, scale in CLAIMS:
        m = re.search(pattern, readme_text, re.DOTALL)
        if m:
            out[metric] = _num(m.group(1)) * scale
    return out


def load_artifact_metrics(path: str | None, search_dir: str):
    """``(artifact_name, {metric: value})`` — delegated to the shared
    ``bench_artifacts.load_newest_metrics`` so this script and
    bench.py's regression tripwire can never resolve "the newest parsed
    artifact" differently."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if root not in sys.path:
        sys.path.insert(0, root)
    import bench_artifacts

    return bench_artifacts.load_newest_metrics(search_dir, path)


def main(argv=None) -> int:
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ap = argparse.ArgumentParser(prog="check_readme_claims")
    ap.add_argument("--readme", default=os.path.join(here, "README.md"))
    ap.add_argument("--artifact", default=None,
                    help="a specific bench artifact (default: newest "
                         "parsed BENCH_r*.json in the repo root)")
    ap.add_argument("--tolerance", type=float, default=0.35,
                    help="allowed |claim/measured - 1| (default 0.35)")
    ap.add_argument("--strict", action="store_true",
                    help="claims whose metric the artifact lacks FAIL "
                         "instead of warning")
    args = ap.parse_args(argv)

    with open(args.readme) as f:
        claims = extract_claims(f.read())
    if not claims:
        print("check_readme_claims: no perf claims matched in "
              f"{args.readme} — claims table out of date?",
              file=sys.stderr)
        return 1
    ref, measured = load_artifact_metrics(
        args.artifact, os.path.dirname(os.path.abspath(args.readme)))
    if ref is None:
        print("check_readme_claims: no bench artifact with parsed "
              "metrics found — nothing to reconcile")
        return 0

    failures, warnings_, ok = [], [], []
    for metric, claim in sorted(claims.items()):
        got = measured.get(metric)
        if not isinstance(got, (int, float)) or got <= 0:
            warnings_.append(
                f"  ? {metric}: claimed {claim:g}, artifact {ref} has "
                "no such metric")
            continue
        ratio = claim / got
        line = (f"{metric}: claimed {claim:g} vs measured {got:g} "
                f"(x{ratio:.2f})")
        if metric in FLOOR_CLAIMS:
            # one-sided: beating the floor is success, not drift
            bad = got < claim * (1.0 - args.tolerance)
            line += " [floor]"
        elif metric in CEILING_CLAIMS:
            # one-sided the other way: a latency under the ceiling is
            # the feature working; only blowing through it fails
            bad = got > claim * (1.0 + args.tolerance)
            line += " [ceiling]"
        else:
            bad = abs(ratio - 1.0) > args.tolerance
        if bad:
            failures.append("  FAIL " + line)
        else:
            ok.append("  ok   " + line)

    print(f"check_readme_claims: {len(claims)} claims vs {ref} "
          f"(tolerance ±{args.tolerance:.0%})")
    for line in ok + warnings_ + failures:
        print(line)
    if args.strict and warnings_:
        print(f"{len(warnings_)} claims unreconciled (--strict)")
        return 1
    if failures:
        print(f"{len(failures)} claims out of tolerance — update "
              "README.md or investigate the regression")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
