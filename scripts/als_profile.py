"""ALS sweep attribution: scan-wrapped component micro-benchmarks."""
import jax, jax.numpy as jnp
from jax import lax
from tpu_distalg.ops import linalg
from tpu_distalg.utils import profiling, prng

m, n, k, sweeps = 4096, 16384, 64, 50  # bench.py's ALS geometry
key = prng.root_key(0)
U0 = jax.random.normal(jax.random.fold_in(key, 0), (m, k)) * 0.3
V0 = jax.random.normal(jax.random.fold_in(key, 1), (n, k)) * 0.3
R = U0 @ V0.T
Ui = jax.random.normal(jax.random.fold_in(key, 2), (m, k)) * 0.1
Vi = jax.random.normal(jax.random.fold_in(key, 3), (n, k)) * 0.1
HI = lax.Precision.HIGHEST

def scan_bench(name, body):
    @jax.jit
    def run(R, U, V):
        def step(carry, _):
            return body(R, *carry), None
        (U, V), _ = lax.scan(step, (U, V), None, length=sweeps)
        return U, V
    best, _ = profiling.steps_per_sec(lambda: run(R, Ui, Vi), steps=sweeps,
                                      with_stats=True, repeats=3, chain=8)
    print(f"{name}: {best:.0f} /s  ({1e3/best:.3f} ms each)")
    return best

# full sweep (what bench measures, incl rmse)
def full(R, U, V):
    G_v = linalg.gram(V, 0.0, n)
    U = linalg.solve_factor_block(G_v, V, R)
    G_u = linalg.gram(U, 0.0, m)
    V = linalg.solve_factor_block(G_u, U, R.T)
    diff = R - jnp.matmul(U, V.T, precision=HI)
    err = jnp.sqrt(jnp.sum(diff * diff) / (m * n))
    return U + 0 * err, V
scan_bench("full sweep      ", full)

# solves only (no rmse)
def solves(R, U, V):
    G_v = linalg.gram(V, 0.0, n)
    U = linalg.solve_factor_block(G_v, V, R)
    G_u = linalg.gram(U, 0.0, m)
    V = linalg.solve_factor_block(G_u, U, R.T)
    return U, V
scan_bench("solves only     ", solves)

# rmse only
def rmse_only(R, U, V):
    diff = R - jnp.matmul(U, V.T, precision=HI)
    err = jnp.sqrt(jnp.sum(diff * diff) / (m * n))
    return U + 0 * err, V
scan_bench("rmse only       ", rmse_only)

# solves with DEFAULT-precision rhs (precision attribution)
def solves_default(R, U, V):
    FtF = jnp.matmul(V.T, V, precision=HI)
    G_v = FtF + 0.0
    rhs = jnp.matmul(V.T, R.T)
    cho = jax.scipy.linalg.cho_factor(G_v)
    U = jax.scipy.linalg.cho_solve(cho, rhs).T
    FtF2 = jnp.matmul(U.T, U, precision=HI)
    rhs2 = jnp.matmul(U.T, R)
    cho2 = jax.scipy.linalg.cho_factor(FtF2)
    V = jax.scipy.linalg.cho_solve(cho2, rhs2).T
    return U, V
scan_bench("solves DEFAULT  ", solves_default)

# rmse via 3-pass (bf16x3) instead of 6-pass
def rmse_3pass(R, U, V):
    diff = R - jnp.matmul(U, V.T, precision=lax.Precision.HIGH)
    err = jnp.sqrt(jnp.sum(diff * diff) / (m * n))
    return U + 0 * err, V
try:
    scan_bench("rmse HIGH(3pass)", rmse_3pass)
except Exception as e:
    print("rmse HIGH failed:", type(e).__name__)

# blocked rmse: avoid materializing the full (m, n) diff
def rmse_blocked(R, U, V):
    B = 2048
    def blk(c, j):
        Vb = lax.dynamic_slice(V, (j, 0), (B, k))
        Rb = lax.dynamic_slice(R, (0, j), (m, B))
        d = Rb - jnp.matmul(U, Vb.T, precision=HI)
        return c + jnp.sum(d * d), None
    s, _ = lax.scan(blk, jnp.float32(0), jnp.arange(0, n, B))
    err = jnp.sqrt(s / (m * n))
    return U + 0 * err, V
scan_bench("rmse blocked    ", rmse_blocked)

def full_blocked(R, U, V):
    G_v = linalg.gram(V, 0.0, n)
    U = linalg.solve_factor_block(G_v, V, R)
    G_u = linalg.gram(U, 0.0, m)
    V = linalg.solve_factor_block(G_u, U, R.T)
    B = 2048
    def blk(c, j):
        Vb = lax.dynamic_slice(V, (j, 0), (B, k))
        Rb = lax.dynamic_slice(R, (0, j), (m, B))
        d = Rb - jnp.matmul(U, Vb.T, precision=HI)
        return c + jnp.sum(d * d), None
    s, _ = lax.scan(blk, jnp.float32(0), jnp.arange(0, n, B))
    err = jnp.sqrt(s / (m * n))
    return U + 0 * err, V
scan_bench("full blocked    ", full_blocked)
