#!/usr/bin/env bash
# The one lint gate CI (and a pre-commit human) runs: domain rules —
# per-file TDA0xx AND the project-graph TDA1xx interprocedural pass —
# style (ruff, when installed — `tda lint` chains it over the same
# files), and the README↔artifact reconciliation. Any failure fails
# the gate; each tool prints its own findings.
#
#   scripts/lint_gate.sh            # gate the default surface
#   scripts/lint_gate.sh --fix      # apply the mechanically-safe fixes
#                                   # first (TDA021 daemon=, suppression
#                                   # scaffolds/removals), then gate
set -u
cd "$(dirname "$0")/.."

rc=0

# 1. domain lint: per-file rules + the whole-program project graph
#    (chains ruff itself when installed)
python -m tpu_distalg.cli lint tpu_distalg/ tests/ scripts/ bench.py \
    --baseline lint_baseline.json "$@" || rc=1

# 2. the same engine through --format json: a smoke test that the
#    project-graph pass not only finds nothing but RUNS — an engine
#    crash (unparseable summary, resolver recursion, cache decode)
#    must fail the gate even on a findings-clean tree
python -m tpu_distalg.cli lint tpu_distalg/ tests/ scripts/ bench.py \
    --baseline lint_baseline.json --format json --no-ruff \
    > /dev/null || rc=1

# 3. the wire contract: docs/PROTOCOL.md must match what the
#    protocol-graph extractor recovers from source (same docs-never-
#    drift shape as the README reconciliation below)
python -m tpu_distalg.cli protocol --check || rc=1

# 4. the protocol extractor through --format json: engine-crash smoke
#    on the machine-readable path, per the step-2 convention
python -m tpu_distalg.cli protocol --format json > /dev/null || rc=1

# 5. README claims vs recorded bench artifacts
python scripts/check_readme_claims.py || rc=1

if [ "$rc" -ne 0 ]; then
    echo "lint gate: FAILED" >&2
else
    echo "lint gate: OK"
fi
exit "$rc"
