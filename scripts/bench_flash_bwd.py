"""A/B: flash fwd+bwd (Pallas backward) vs XLA-ring backward.

The XLA path's vjp saves every (H, S, chunk) probability tile — H*S^2*4
bytes of residuals (32 GB at S=32k, H=8) — so it plain OOMs beyond ~12k
tokens on a 16 GB chip. The flash backward saves only (O, lse) and
recomputes P per VMEM tile, so 32k+ trains on one chip.
"""
import functools
import jax, jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from tpu_distalg.parallel import DATA_AXIS, data_parallel, get_mesh
from tpu_distalg.parallel.ring import ring_attention
from tpu_distalg.utils import profiling, prng

mesh = get_mesh()
H, d = 8, 128

def make(fn):
    f = data_parallel(fn, mesh, in_specs=(P(DATA_AXIS, None, None),) * 3,
                      out_specs=P(DATA_AXIS, None, None))
    def loss(q_, k_, v_):
        return jnp.sum(f(q_, k_, v_).astype(jnp.float32) ** 2)
    return jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

def run(name, fn, S):
    key = prng.root_key(0)
    q, k, v = (jax.random.normal(jax.random.fold_in(key, i), (S, H, d), jnp.bfloat16)
               for i in range(3))
    g = make(fn)
    best, spread = profiling.steps_per_sec(lambda: g(q, k, v), steps=1,
                                           with_stats=True, repeats=3, chain=4)
    flops = S * S / 2 * d * H * 2 * 2 * 3.5   # causal fwd + 2.5x bwd
    print(f"{name} S={S}: {best:.2f} calls/s -> {flops*best/1e12:.1f} TFLOP/s fwd+bwd  spread={spread}", flush=True)

flash = functools.partial(ring_attention, causal=True, use_flash=True)
xla = functools.partial(ring_attention, causal=True, kv_chunk=1024)
run("flash", flash, 8192)
run("xla  ", xla, 8192)
run("flash", flash, 32768)
try:
    run("xla  ", xla, 32768)
except Exception as e:
    print(f"xla   S=32768: OOM ({type(e).__name__})", flush=True)
