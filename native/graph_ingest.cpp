// Native graph-ingest runtime for tpu-distalg.
//
// The reference leans on Spark's JVM shuffle machinery for its graph
// preprocessing — `links.distinct().groupByKey()` (reference
// graph_computation/pagerank.py:41) and the join/union/distinct closure
// pipeline (transitive_closure.py:27-40). The TPU build does that set
// algebra once, host-side, before arrays ever reach the devices; this
// library is the native (C++) implementation of that preprocessing so the
// host step is not a Python/NumPy bottleneck at 10M+ edge scale.
//
// Exposed via a C ABI for ctypes (no pybind11 in the image). All functions
// are thread-safe and allocation-free: callers (NumPy) own every buffer.

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

// Sort (src,dst) pairs and drop duplicates in place.
// Returns the deduplicated edge count. Buffers are modified in place.
int64_t tda_dedupe_edges(int64_t* src, int64_t* dst, int64_t n) {
  if (n <= 0) return 0;
  std::vector<uint64_t> packed;  // works for vertex ids < 2^32
  bool small = true;
  for (int64_t i = 0; i < n; ++i) {
    if (src[i] < 0 || dst[i] < 0 || src[i] > 0xffffffffLL ||
        dst[i] > 0xffffffffLL) {
      small = false;
      break;
    }
  }
  if (small) {
    packed.resize(n);
    for (int64_t i = 0; i < n; ++i)
      packed[i] = (static_cast<uint64_t>(src[i]) << 32) |
                  static_cast<uint64_t>(dst[i]);
    std::sort(packed.begin(), packed.end());
    auto end = std::unique(packed.begin(), packed.end());
    int64_t m = static_cast<int64_t>(end - packed.begin());
    for (int64_t i = 0; i < m; ++i) {
      src[i] = static_cast<int64_t>(packed[i] >> 32);
      dst[i] = static_cast<int64_t>(packed[i] & 0xffffffffULL);
    }
    return m;
  }
  // general path: index sort
  std::vector<int64_t> idx(n);
  for (int64_t i = 0; i < n; ++i) idx[i] = i;
  std::sort(idx.begin(), idx.end(), [&](int64_t a, int64_t b) {
    return src[a] != src[b] ? src[a] < src[b] : dst[a] < dst[b];
  });
  std::vector<int64_t> s2(n), d2(n);
  int64_t m = 0;
  for (int64_t k = 0; k < n; ++k) {
    int64_t i = idx[k];
    if (m == 0 || s2[m - 1] != src[i] || d2[m - 1] != dst[i]) {
      s2[m] = src[i];
      d2[m] = dst[i];
      ++m;
    }
  }
  std::memcpy(src, s2.data(), m * sizeof(int64_t));
  std::memcpy(dst, d2.data(), m * sizeof(int64_t));
  return m;
}

// Out-degree histogram over deduplicated edges (multi-threaded).
void tda_out_degree(const int64_t* src, int64_t n_edges, int32_t* degree,
                    int64_t n_vertices) {
  std::memset(degree, 0, n_vertices * sizeof(int32_t));
  unsigned hw = std::thread::hardware_concurrency();
  int n_threads = hw ? static_cast<int>(hw) : 4;
  if (n_edges < (1 << 16) || n_threads <= 1) {
    for (int64_t i = 0; i < n_edges; ++i) degree[src[i]]++;
    return;
  }
  std::vector<std::vector<int32_t>> partial(
      n_threads, std::vector<int32_t>(n_vertices, 0));
  std::vector<std::thread> threads;
  int64_t chunk = (n_edges + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; ++t) {
    threads.emplace_back([&, t] {
      int64_t lo = t * chunk, hi = std::min(n_edges, lo + chunk);
      auto& mine = partial[t];
      for (int64_t i = lo; i < hi; ++i) mine[src[i]]++;
    });
  }
  for (auto& th : threads) th.join();
  for (int t = 0; t < n_threads; ++t)
    for (int64_t v = 0; v < n_vertices; ++v) degree[v] += partial[t][v];
}

// CSR row offsets from sorted src ids: offsets has n_vertices+1 slots.
void tda_csr_offsets(const int64_t* sorted_src, int64_t n_edges,
                     int64_t* offsets, int64_t n_vertices) {
  int64_t e = 0;
  offsets[0] = 0;
  for (int64_t v = 0; v < n_vertices; ++v) {
    while (e < n_edges && sorted_src[e] == v) ++e;
    offsets[v + 1] = e;
  }
}

// Stable counting-sort permutation of bounded integer keys: perm[k] is
// the index of the k-th smallest key (ties in input order). O(n + range),
// single pass — the host-side prep behind PageRank's dst-sorted edge
// layout, where np.argsort(kind='stable') is the NumPy bottleneck at
// 10M+ edges. Keys must lie in [0, range); returns 0 on success, -1 if a
// key is out of range.
int32_t tda_counting_sort_perm(const int64_t* keys, int64_t n,
                               int64_t range, int64_t* perm) {
  for (int64_t i = 0; i < n; ++i)
    if (keys[i] < 0 || keys[i] >= range) return -1;
  std::vector<int64_t> counts(range + 1, 0);
  for (int64_t i = 0; i < n; ++i) counts[keys[i] + 1]++;
  for (int64_t v = 0; v < range; ++v) counts[v + 1] += counts[v];
  for (int64_t i = 0; i < n; ++i) perm[counts[keys[i]]++] = i;
  return 0;
}

// Interleave dst-sorted edge columns into the packed csr_edge_blocks_i32
// cache rows: out[3i..3i+2] = [src, dst, bits(w)] as int32 (the f32 weight
// travels as its bit pattern so the whole row matrix is one dtype — the
// packed-cache format holds exactly one). Vertex ids must fit int32 (the
// cache layout's id width); callers validate the range. Multi-threaded:
// the row interleave is the last O(E) host pass of a 10M+ edge ingest.
void tda_pack_edge_rows(const int64_t* src, const int64_t* dst,
                        const float* w, int64_t n, int32_t* out) {
  unsigned hw = std::thread::hardware_concurrency();
  int n_threads = (n >= (1 << 20) && hw > 1) ? static_cast<int>(hw) : 1;
  auto pack = [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      out[3 * i] = static_cast<int32_t>(src[i]);
      out[3 * i + 1] = static_cast<int32_t>(dst[i]);
      std::memcpy(&out[3 * i + 2], &w[i], sizeof(int32_t));
    }
  };
  if (n_threads <= 1) {
    pack(0, n);
    return;
  }
  std::vector<std::thread> threads;
  int64_t chunk = (n + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; ++t) {
    int64_t lo = t * chunk, hi = std::min(n, lo + chunk);
    if (lo < hi) threads.emplace_back(pack, lo, hi);
  }
  for (auto& th : threads) th.join();
}

// Parse a whitespace-delimited "src dst" text edge list (comments: lines
// starting with '#'). Returns edges read, or -1 on open failure, or -2 if
// the caller's capacity was too small.
int64_t tda_parse_edges_text(const char* path, int64_t* src, int64_t* dst,
                             int64_t capacity) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  int64_t n = 0;
  char line[256];
  while (std::fgets(line, sizeof line, f)) {
    if (line[0] == '#' || line[0] == '\n') continue;
    char* endp = nullptr;
    long long a = std::strtoll(line, &endp, 10);
    if (endp == line) continue;
    long long b = std::strtoll(endp, nullptr, 10);
    if (n >= capacity) {
      std::fclose(f);
      return -2;
    }
    src[n] = a;
    dst[n] = b;
    ++n;
  }
  std::fclose(f);
  return n;
}

}  // extern "C"
