"""Benchmark driver — prints ONE JSON line.

Metric: SSGD logistic-regression steps/sec/chip (BASELINE.json) on a
1M-row synthetic two-class task (125 features + bias; with the packed
label/validity columns the design matrix is exactly 128-wide — one lane
tile), minibatch fraction 0.1 — the reference's ``optimization/ssgd.py``
schedule at benchmark scale.

On TPU the step runs the packed one-pass Pallas kernel
(``sampler='fused'``: sampling + forward + backward in a single HBM pass
over X, bf16); elsewhere it falls back to the XLA Bernoulli-mask path so
the bench still runs on CPU meshes.

Baseline: the reference launches one Spark job per SGD step
(``ssgd.py:93-103``); PySpark is not installed in this image (no JVM), so
the baseline is a *generous* estimate of local-mode Spark job throughput:
BASELINE_STEPS_PER_SEC = 20 jobs/sec (50 ms/job scheduling+pickling floor;
real local[*] measurements are typically 10-30 jobs/sec for trivial jobs,
and far worse at 1M rows). ``vs_baseline`` = our steps/sec ÷ that.
"""

import json
import os
import threading
import time

N_ROWS = 1 << 20
N_FEATURES = 125
N_STEPS = 200  # steps per timed scan segment
N_REPEATS = 3
BASELINE_STEPS_PER_SEC = 20.0
WATCHDOG_SECONDS = int(os.environ.get("BENCH_WATCHDOG_SECONDS", 1800))


def _watchdog():
    """If the device never comes up (e.g. a wedged TPU tunnel), emit an
    honest zero-value metric line instead of hanging the harness forever."""
    time.sleep(WATCHDOG_SECONDS)
    print(json.dumps({
        "metric": "ssgd_lr_steps_per_sec_per_chip",
        "value": 0.0,
        "unit": "steps/s/chip",
        "vs_baseline": 0.0,
    }), flush=True)
    os._exit(2)


def main():
    threading.Thread(target=_watchdog, daemon=True).start()
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_distalg.models import ssgd
    from tpu_distalg.ops import logistic
    from tpu_distalg.parallel import get_mesh, parallelize
    from tpu_distalg.utils import datasets, prng

    mesh = get_mesh()
    n_chips = len(jax.devices())
    on_tpu = next(iter(mesh.devices.flat)).platform == "tpu"

    X, y = datasets.synthetic_two_class(N_ROWS, N_FEATURES, seed=0)
    X = datasets.add_bias_column(X)
    d = X.shape[1]

    if on_tpu:
        config = ssgd.SSGDConfig(
            n_iterations=N_STEPS, eval_test=False,
            x_dtype="bfloat16", sampler="fused", init_seed=7,
        )
        fn, X2, w0, meta = ssgd.prepare_fused(X, y, mesh, config)
        dummy = jnp.zeros((1,), jnp.float32)
        ev = (jnp.zeros((1, meta["d_total"]), jnp.float32),
              jnp.zeros((1,), jnp.float32))
        args = (X2, dummy, dummy, ev[0], ev[1])
    else:
        config = ssgd.SSGDConfig(n_iterations=N_STEPS, eval_test=False)
        Xs, ys = parallelize(X, mesh), parallelize(y, mesh)
        w0 = logistic.init_weights(prng.root_key(7), d)
        fn = ssgd.make_train_fn(mesh, config, Xs.n_padded)
        ev = jnp.zeros((1, d), jnp.float32), jnp.zeros((1,), jnp.float32)
        args = (Xs.data, ys.data, Xs.mask, ev[0], ev[1])

    def run(w):
        # NOTE: device timing via host fetch — on tunneled TPU backends
        # block_until_ready can return before execution finishes
        w2, _ = fn(*args, w)
        np.asarray(w2)
        return w2

    w = run(w0)  # warmup / compile
    best = 0.0
    for r in range(N_REPEATS):
        t0 = time.perf_counter()
        w = run(w)
        dt = time.perf_counter() - t0
        best = max(best, N_STEPS / dt)

    per_chip = best / n_chips
    print(json.dumps({
        "metric": "ssgd_lr_steps_per_sec_per_chip",
        "value": round(per_chip, 2),
        "unit": "steps/s/chip",
        "vs_baseline": round(per_chip / BASELINE_STEPS_PER_SEC, 2),
    }))


if __name__ == "__main__":
    main()
