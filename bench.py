"""Benchmark driver — prints ONE JSON line per metric (SSGD first).

Headline metrics (BASELINE.json):
  1. SSGD logistic-regression steps/sec/chip on a 1M-row synthetic
     two-class task (125 features + bias; with the packed label/validity
     columns the design matrix is exactly 128 wide — one lane tile),
     minibatch fraction 0.1 — the reference's ``optimization/ssgd.py``
     schedule at benchmark scale.
  2. PageRank iterations/sec on a 1M-vertex, ~8M-edge Erdős–Rényi graph
     (``graph_computation/pagerank.py:50-57`` at benchmark scale).

Additional recorded lines (TPU only): 100M-row SSGD with on-device
synthesis (host RAM O(1)), 1B-row virtual SSGD (>HBM, regenerated
rows), 32 GB streamed SSGD (>HBM of real disk bytes), the
MA/BMUF/EASGD local-step rate (megakernel local rounds), 10M-point
k-means, 4096×16384 rank-64 ALS (exact recovery AND the noisy
ridge-regularized instance), causal flash attention (32k fwd, 32k
fwd+bwd, 128k fwd, 128k fwd+bwd), and the data-subsystem >HBM lines
(18.3 GB streamed minibatch k-means, 17.2 GB epoch-streamed ALS —
``tpu_distalg/data/``) — each with spread and, where the workload is
HBM-bound, its roofline fraction.

The summary line also carries a perf-regression TRIPWIRE: every metric
is compared against the newest parsed ``BENCH_r*.json`` artifact and
>15% drops are flagged in a ``regressions`` map next to
``all_metrics`` (``scripts/check_readme_claims.py`` reconciles the
README's claims against the same artifact).

On TPU the SSGD step runs the whole-schedule megakernel on single-shard
meshes (``sampler='fused_train'``: weights in VMEM, update in-kernel,
one Mosaic launch per 125 steps) and the traffic-proportional
block-gather kernel on dp>1 meshes (``sampler='fused_gather'``: per
step, sample frac·n_blocks block ids XLA-side and DMA ONLY those blocks
— HBM traffic ≈ fraction × |X|); elsewhere it falls back to the XLA
Bernoulli-mask path so the bench still runs on CPU meshes. Steps are timed over ``N_STEPS``-long jitted scans —
the reference's whole-schedule-in-one-program shape — so per-call
dispatch overhead (large on tunneled TPU rigs) is amortized exactly the
way a real training run amortizes it; ``N_CHAIN`` back-to-back async
calls per timed repeat amortize the tunnel's ~100 ms dispatch+fetch
round-trip too (one 1500-step segment is only ~70 ms of device time, so
chain=1 timing would charge ~60 us/step of host round-trip to the
device).

Baseline: the reference launches one Spark job per SGD step
(``ssgd.py:93-103``). PySpark is not installable here (no JVM), so the
baseline is MEASURED as the same SSGD update executed in the reference's
driver-loop shape — one jit call + host round-trip per step, no scan —
which is the per-step dispatch pattern Spark's driver pays before any of
its scheduling/pickling/shuffle costs. Every ``vs_baseline`` divides by
``max(measured, floor)`` where the floor models an idealized Spark
driver launching 20 jobs/s serially while paying the same per-iteration
device compute the scanned path achieves (``_floor_denominator``) — a
slow rig (the tunnel charges ~100 ms per driver round-trip) can only
make the claim more conservative, never less. Both the measured rate
and the floor are recorded in each line.

The LAST stdout line repeats every metric in one compact
``all_metrics`` map (``_emit_summary``) so a tail-capturing driver
always records the flagship numbers.

Observability (round 6): backend init runs under
``telemetry.supervisor`` (per-attempt deadline + retries — the r5 bench
died to a 26-minute SILENT init hang), each bench phase is a telemetry
span, and a ``telemetry.heartbeat`` watchdog emits the summary and
exits 2 when no phase marks progress for ``WATCHDOG_SECONDS`` — with
the stuck phase named in the event log. A second absolute timer
(``HARD_DEADLINE_SECONDS``) prints the summary-so-far WITHOUT exiting,
so even a slow-but-alive run that outlives the external driver's
window leaves a parseable artifact. ``--telemetry-dir DIR`` (or
``$TDA_TELEMETRY_DIR``) records the JSONL log; ``tda report DIR``
summarizes it.

Convergence evidence (recorded every round): the breast-cancer task is
trained to 1500 iterations with each fused kernel and the final test
accuracy is emitted in the SSGD JSON line (reference golden 0.929825,
``ssgd.py:130``).
"""

import json
import os
import socket
import sys
import threading
import time

from tpu_distalg.telemetry import events as tevents
from tpu_distalg.telemetry import heartbeat as theartbeat
from tpu_distalg.telemetry import supervisor as tsupervisor

N_ROWS = 1 << 20
N_FEATURES = 125
N_STEPS = 1500          # steps per timed scan segment (reference schedule)
N_REPEATS = 3
# back-to-back async calls per timed repeat: one 1500-step segment runs
# ~70 ms on device while the tunnel's dispatch+fetch round-trip is
# ~100 ms — timing a single call would charge ~60 us/step of HOST
# round-trip to the DEVICE rate (measured: a trivial 1500-step scan
# "costs" 63 us/step at chain=1, 4.5 us/step at chain=16). Chaining
# amortizes the round-trip to ~2 us/step; still conservative (see
# utils/profiling.steps_per_sec).
N_CHAIN = 32
GATHER_BLOCK_ROWS = 8192
ASSUMED_SPARK_JOBS_PER_SEC = 20.0
PR_VERTICES = 1_000_000
PR_AVG_DEGREE = 8.0
PR_ITERS_PER_CALL = 50
# the out-of-core graph line (ROADMAP item 3, two orders past the 1M
# resident line): 100M vertices × avg in-degree 16 ≈ 1.6B edges → the
# 12 B/edge block cache is ~19.2 GB on disk, 1.2× one v5e's HBM — the
# edge set CANNOT be resident, proving the streamed sweep at scale
PR100M_VERTICES = 100_000_000
PR100M_AVG_IN_DEGREE = 16.0
PR100M_ALPHA = 1.6
PR100M_ITERS = 2  # each sweep streams the full cache from disk
V5E_HBM_BYTES_PER_SEC = 819e9
WATCHDOG_SECONDS = int(os.environ.get("BENCH_WATCHDOG_SECONDS", 3600))
INIT_RETRY_ATTEMPTS = 40   # backend-init attempt CEILING — the actual
INIT_RETRY_SECONDS = 60    # count is capped by the remaining hard-
#                            deadline budget (_init_retry_budget);
#                            per-attempt deadline below
INIT_TIMEOUT_SECONDS = float(os.environ.get(
    "BENCH_INIT_TIMEOUT_SECONDS", 300))  # covers the init-HANGS mode
# ^ 3600: a cold rig pays a one-time ~15 min generation of the 32 GB
# streamed-dataset cache on top of the ~10 min bench proper; the
# watchdog is a hang detector, not a time budget — it still emits the
# all-metrics summary when it fires. Since round 6 it is a PHASE-stall
# detector (telemetry.heartbeat over the per-phase marks), so a wedged
# device dies with the stuck phase named in the telemetry log instead
# of an anonymous absolute timer.


_SUMMARY = {}
# full metric line objects in emission order: the hard-deadline path
# RE-EMITS them (r5 regression: a timed-out run's tail held only a
# torn partial line — rc 124, parsed null — because the last full
# lines had scrolled past the driver's capture window)
_LINES = []
# ONE lock serializes _SUMMARY mutation AND the stdout prints: the
# heartbeat's stall path emits the summary from its daemon thread while
# the main thread may be mid-_emit — unlocked, the two prints could
# splice the single tail line the driver parses, and the summary's dict
# comprehension could see a concurrent insert (RuntimeError). RLock:
# _emit_summary emits through _emit while already holding it.
_EMIT_LOCK = threading.RLock()
_T0 = time.monotonic()   # bench start — the hard-deadline budget clock
# set to "cpu" when the CPU-fallback tier is driving the round: every
# metric line and the summary carry the tag, so the artifact can never
# masquerade as a TPU round (bench_artifacts skips cpu-tagged artifacts
# when resolving the claims/tripwire reference)
_BACKEND_TAG = None
# the RigProfile driving this round's tuned A/B phases (set by
# ensure_profile / _rig_profile); the summary line carries it — or
# "untuned" — so bench_artifacts can refuse to reconcile claims
# against a profile measured on a different rig
_TUNE_PROFILE_ID = None


def _emit(obj):
    """Print one metric line AND record it for the end-of-run summary.
    The driver keeps only the TAIL of stdout (r4 verdict: two rounds of
    flagship numbers evaporated because SSGD prints first), so
    :func:`_emit_summary` re-prints every recorded metric in one compact
    final line. Each line is also mirrored into the telemetry log as a
    ``metric`` event (``--telemetry-dir``)."""
    with _EMIT_LOCK:
        _SUMMARY[obj["metric"]] = {
            "value": obj["value"], "unit": obj["unit"],
            "vs_baseline": obj.get("vs_baseline")}
        _LINES.append(dict(obj))
        print(json.dumps(obj), flush=True)
    tevents.emit("metric", **obj)


REGRESSION_DROP_FRACTION = 0.15


def _load_prev_metrics():
    """Newest parsed ``BENCH_r*.json`` next to this file, as
    ``(artifact_name, {metric: value})`` — the perf-regression
    tripwire's reference, resolved by the SAME loader the README
    reconciliation script uses (``bench_artifacts.py``)."""
    import bench_artifacts

    return bench_artifacts.load_newest_metrics(
        os.path.dirname(os.path.abspath(__file__)))


def _regressions():
    """Tripwire (VERDICT weak #5): every metric of THIS run that
    regressed >15% against the newest recorded bench artifact, flagged
    in the summary line instead of silently shipping slower. Recorded
    metrics are rates (higher is better) except the latency metrics in
    ``LOWER_IS_BETTER_METRICS``, which flag on a RISE — a p99 falling
    is the feature working, not a regression. Caller holds
    _EMIT_LOCK."""
    ref, prev = _load_prev_metrics()
    if ref is None:
        return None, {}
    flags = {}
    for name, rec in _SUMMARY.items():
        pv, cur = prev.get(name), rec["value"]
        if not (isinstance(pv, (int, float)) and pv > 0
                and isinstance(cur, (int, float))):
            continue
        if name in LOWER_IS_BETTER_METRICS:
            if cur > (1.0 + REGRESSION_DROP_FRACTION) * pv:
                flags[name] = {"prev": pv, "now": cur,
                               "rise": round(cur / pv - 1.0, 3)}
        elif cur < (1.0 - REGRESSION_DROP_FRACTION) * pv:
            flags[name] = {"prev": pv, "now": cur,
                           "drop": round(1.0 - cur / pv, 3)}
    return ref, flags


def _emit_summary():
    """The LAST stdout line: flagship metric in the driver's schema plus
    an ``all_metrics`` map of every line printed this run — the tail
    alone now reproduces every headline number — and the
    perf-regression tripwire verdict against the newest recorded
    artifact (``regressions`` non-empty = some metric dropped >15%)."""
    flag = "ssgd_lr_steps_per_sec_per_chip"
    with _EMIT_LOCK:
        head = _SUMMARY.get(
            flag,
            {"value": 0.0, "unit": "steps/s/chip", "vs_baseline": None})
        if _BACKEND_TAG == "cpu":
            # a CPU-fallback round's values are not comparable to the
            # TPU reference — flagging every metric as a "regression"
            # would drown the tripwire in noise
            ref, regressions = None, {}
        else:
            ref, regressions = _regressions()
        _emit({
            "metric": flag,
            "value": head["value"],
            "unit": head["unit"],
            "vs_baseline": head["vs_baseline"],
            **({"backend": _BACKEND_TAG} if _BACKEND_TAG else {}),
            "rig": socket.gethostname(),
            "tune_profile": _TUNE_PROFILE_ID or "untuned",
            "all_metrics": {k: v["value"] for k, v in _SUMMARY.items()},
            "all_units": {k: v["unit"] for k, v in _SUMMARY.items()},
            "all_vs_baseline": {k: v["vs_baseline"]
                                for k, v in _SUMMARY.items()
                                if v["vs_baseline"] is not None},
            **({"regression_ref": ref, "regressions": regressions}
               if ref is not None else {}),
        })


def _floor_denominator(measured, scan_rate_total):
    """``vs_baseline`` denominator with an assumed-floor guard on EVERY
    driver-loop baseline (r4 verdict: the tunneled rig charges ~100 ms
    host round-trip per driver iteration, so the ALS measured baseline
    came out 600x slower than the same loop on a local rig — the ratio
    measured the tunnel, not the architecture). The floor models the
    best driver the reference's architecture permits: a Spark master
    launching ``ASSUMED_SPARK_JOBS_PER_SEC`` jobs/s serially with the
    same per-iteration device compute the scanned path achieves
    (1 / (1/jobs + t_iter)). Returns ``(denominator, floor)`` so both
    are recorded next to the measured rate."""
    floor = 1.0 / (1.0 / ASSUMED_SPARK_JOBS_PER_SEC
                   + 1.0 / scan_rate_total)
    return max(measured, floor), floor


def _hbm_fraction(bytes_per_step, steps_per_sec, n_shards):
    """Per-chip fraction of the HBM roofline: per-chip bytes (global
    bytes_per_step / n_shards) × the TOTAL step rate — correct on
    (data, model>1) meshes too, where chip count != data-shard count."""
    return round(
        bytes_per_step * steps_per_sec
        / (n_shards * V5E_HBM_BYTES_PER_SEC), 4)


def _measured_driver_baseline(one_iter, n_base: int = 10):
    """Rate of ``one_iter()`` — ONE driver-shaped iteration: a jit
    dispatch plus a host round-trip that fetches (part of) the result,
    exactly the reference's job-per-iteration execution shape minus all
    Spark overheads. The callable owns any state threading (e.g.
    feeding the fetched weights back in); the first call compiles and
    is not timed. Shared by the SSGD/k-means/PageRank/ALS baselines so
    the timing methodology lives in one place."""
    one_iter()  # compile
    t0 = time.perf_counter()
    for _ in range(n_base):
        one_iter()
    return n_base / (time.perf_counter() - t0)


def _scale_spread(spread, factor, ndigits=1):
    """Re-express a steps_per_sec spread in the METRIC's unit: every
    best/median/min entry is multiplied by the same factor that maps
    the raw call rate to the reported value, so the spread reads
    side-by-side with it (r3 verdict: a tokens/s value next to a
    calls/s spread is unreadable)."""
    out = dict(spread)
    for k in ("best", "median", "min"):
        if k in out:
            out[k] = round(out[k] * factor, ndigits)
    return out


HARD_DEADLINE_SECONDS = int(os.environ.get(
    "BENCH_HARD_DEADLINE_SECONDS", 3 * WATCHDOG_SECONDS))


def _emit_deadline_summary():
    """Re-emit every successfully measured metric line, then the
    summary — the artifact-parseability payload of the hard-deadline
    path, separated out so tests can drive it without the sleep."""
    with _EMIT_LOCK:
        for obj in list(_LINES):
            print(json.dumps(obj), flush=True)
        _emit_summary()


def _init_attempt_timeout(init_seconds=None):
    """Per-attempt backend-init deadline: the hardcoded worst-case cap,
    SHRUNK to 3x the rig's MEASURED init time when the RigProfile
    carries one (``tda tune`` records ``backend_init_s``) — a backend
    whose healthy init takes 8 s should be declared hung after ~24 s,
    not after the 5-minute cap sized for a cold tunneled TPU (r05's
    26-minute retry tail was this cap times a handful of attempts)."""
    if not isinstance(init_seconds, (int, float)) or init_seconds <= 0:
        return INIT_TIMEOUT_SECONDS
    return min(INIT_TIMEOUT_SECONDS, max(10.0, 3.0 * init_seconds))


def _init_retry_budget(remaining_seconds, init_seconds=None):
    """Backend-init RETRIES whose total attempt count (retries + the
    first attempt) fits half the remaining hard-deadline budget (r5
    regression: 40 fixed attempts x ~6 min = 4 h of retrying inside a
    3 h window — the driver's SIGKILL landed while init was still
    spinning and the artifact parsed null); the other half stays
    reserved for the bench proper. ``init_seconds`` (the profile's
    measured backend-init time) re-prices each attempt via
    :func:`_init_attempt_timeout`, so a fast-init rig gets MORE
    retries inside the same budget instead of burning it on the
    worst-case cap."""
    per_attempt = _init_attempt_timeout(init_seconds) \
        + INIT_RETRY_SECONDS
    attempts = int((remaining_seconds * 0.5) // per_attempt)
    return max(0, min(INIT_RETRY_ATTEMPTS - 1, attempts - 1))


def _hard_deadline():
    """Belt-and-braces artifact guarantee: a slow-but-ALIVE run keeps
    marking progress and never trips the phase-stall watchdog, so if it
    outlives the external driver's window the SIGKILL would leave no
    summary (the r5 empty-artifact mode, progressing-slowly variant).
    At the hard deadline every successfully measured metric line is
    RE-EMITTED (the r5 rc-124 run's tail held none of them) followed by
    the summary, WITHOUT exiting. Single-shot — the periodic refresh
    afterwards lives in :func:`_hard_deadline_loop` (the thread
    target), so this stays directly testable."""
    time.sleep(HARD_DEADLINE_SECONDS)
    tevents.emit("hard_deadline", seconds=HARD_DEADLINE_SECONDS)
    _emit_deadline_summary()


def _hard_deadline_loop():
    """Daemon-thread body: the deadline emit, then a summary re-print
    every 10 minutes — whenever the external SIGKILL lands, a complete
    summary line sits within a few lines of the stdout tail and the
    artifact stays parseable."""
    _hard_deadline()
    while True:
        time.sleep(600)
        _emit_summary()


def _watchdog_fire(phase, age):
    """Stall action for the telemetry heartbeat: if no bench phase
    marks progress for WATCHDOG_SECONDS (a wedged device, a dead TPU
    tunnel), emit the summary of everything recorded SO FAR — flagship
    zeroed only if it never ran — instead of hanging the harness
    forever. The heartbeat has already written the ``stall`` event
    naming the stuck phase. os._exit skips main()'s finally, so the
    summary must be printed here."""
    with _EMIT_LOCK:
        _SUMMARY.setdefault(
            "ssgd_lr_steps_per_sec_per_chip",
            {"value": 0.0, "unit": "steps/s/chip", "vs_baseline": 0.0})
        _emit_summary()
    sink = tevents.get_sink()
    if sink is not None:
        sink.close()  # os._exit skips atexit: flush counters + run_end
    os._exit(2)


def _phase(name, fn, *args):
    """Run one bench phase inside a telemetry span: timed, stall-marked
    (the heartbeat names this phase if the device wedges inside it),
    and recorded in the event log for ``tda report``."""
    with tevents.span(f"bench:{name}"):
        return fn(*args)


def _phase_optional(name, fn, *args):
    """Like :func:`_phase` but a failure is RECORDED (telemetry event +
    stderr) instead of sinking the phases after it — the >HBM streamed
    phases build multi-GB disk caches whose environment (free disk) the
    established metrics must not depend on."""
    try:
        return _phase(name, fn, *args)
    except Exception as e:  # noqa: BLE001 — recorded, run continues
        tevents.emit("phase_error", phase=name,
                     error=f"{type(e).__name__}: {e}")
        print(f"[bench] optional phase {name} failed: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
        return None


#: the schedules the comm-comparison phase records every round
COMM_SCHEDULES = ("dense", "bucketed", "bf16", "int8", "topk", "hier")
#: the data-axis size the README's reduction claims are pinned to (the
#: multichip dryrun's mesh): the top-k/int8 wire-reduction factor
#: depends on the shard count, so the claim-reconciled metric is only
#: emitted at this one geometry — other meshes still get the full
#: per-schedule lines with their own achieved reduction
COMM_CANONICAL_SHARDS = 4


def comm_comparison_task():
    """The comm phase's train/test split: 4096/1024 rows of the
    normalized synthetic two-class task (+bias) — conditioned so 1500
    SSGD iterations CONVERGE (every schedule reaches the same 0.7646
    on CPU; topk 0.7656), making equal-or-better a meaningful claim.
    Shared with the multichip dryrun so the two artifacts compare the
    same task."""
    from tpu_distalg.utils import datasets

    X, y = datasets.synthetic_two_class(4096 + 1024, 30, seed=0)
    X = datasets.add_bias_column(X)
    return X[:4096], y[:4096], X[4096:], y[4096:]


def run_comm_comparison(mesh, emit, schedules=COMM_SCHEDULES,
                        iters=1500):
    """Dense vs compressed gradient sync at equal converged metric
    (the comms-layer acceptance evidence): a well-conditioned
    synthetic two-class task trained to the full ``iters`` iterations
    under each schedule, with the per-sync wire bytes from the comm
    layer's accounting and the final test accuracy side by side —
    int8 must cut ``comm.bytes_wire`` >=3x and topk >=4x vs dense
    WITHOUT giving up the converged metric. (The breast-cancer task's
    raw features make its SGD endpoint oscillate +-2-5pt — useless
    for an equal-metric claim; the normalized synthetic task converges
    to the same point under every schedule.)

    SHARED by bench.py's comm phase and the multichip dryrun —
    ``emit`` receives each line dict, so the two artifacts can never
    drift apart in metric/field names. The claim-reconciled
    ``ssgd_comm_*_wire_reduction_vs_dense`` metrics are emitted only
    at the canonical :data:`COMM_CANONICAL_SHARDS` geometry (the
    reduction factor depends on the shard count)."""
    import jax

    from tpu_distalg.models import ssgd

    data = comm_comparison_task()
    d = data[0].shape[1]
    n_shards = int(mesh.shape["data"])
    base_wire = base_acc = None
    for sched in schedules:
        cfg = ssgd.SSGDConfig(n_iterations=iters, comm=sched,
                              eval_every=max(1, iters // 10))
        t0 = time.perf_counter()
        res = ssgd.train(*data, mesh, cfg)
        jax.block_until_ready(res.w)
        dt = time.perf_counter() - t0
        st = ssgd._comm_sync(mesh, cfg, d).stats()
        acc = round(res.final_acc, 6)
        if sched == "dense":
            base_wire, base_acc = st["bytes_wire"], acc
        red = (round(base_wire / st["bytes_wire"], 2)
               if base_wire and st["bytes_wire"] else None)
        emit({
            "metric": f"ssgd_comm_{sched}_bytes_wire_per_sync",
            "value": st["bytes_wire"],
            "unit": "bytes/sync/shard",
            "vs_baseline": None,
            "bytes_logical_per_sync": st["bytes_logical"],
            "rounds_per_sync": st["rounds"],
            "wire_reduction_vs_dense": red,
            "final_acc": acc,
            "acc_delta_vs_dense": (round(acc - base_acc, 6)
                                   if base_acc is not None else None),
            "n_iterations": iters,
            "n_shards": n_shards,
            "seconds_total_including_compile": round(dt, 2),
            "task": "synthetic two-class 4096/1024 (+bias), "
                    "converged at 1500 iters",
        })
        if sched in ("int8", "topk") and red \
                and n_shards == COMM_CANONICAL_SHARDS:
            # the acceptance pair as first-class metrics (value = the
            # reduction factor), so the README claim reconciles
            # directly against the artifact — pinned to the one
            # geometry the claim names
            emit({
                "metric": f"ssgd_comm_{sched}_wire_reduction_vs_dense",
                "value": red,
                "unit": "x",
                "vs_baseline": None,
                "final_acc": acc,
                "acc_delta_vs_dense": round(acc - base_acc, 6),
                "note": f"at the canonical {COMM_CANONICAL_SHARDS}-"
                        f"shard comparison geometry (the factor "
                        f"depends on shard count)",
            })


def _bench_comm(mesh, n_chips):
    """The comm-comparison phase — see :func:`run_comm_comparison`."""
    run_comm_comparison(mesh, _emit)


#: comm-bound geometry for the measured step-time comparison: a wide
#: model (4 MB f32 gradient) over a tiny per-shard row count, so the
#: per-step sync dominates the matvec — the regime the compressed
#: schedules exist for
COMM_SPEEDUP_D = 1 << 20
COMM_SPEEDUP_ROWS_PER_SHARD = 8


def run_comm_step_speedup(mesh, emit, *, d=COMM_SPEEDUP_D,
                          rows_per_shard=COMM_SPEEDUP_ROWS_PER_SHARD,
                          steps=30, repeats=3):
    """MEASURED step-time of the native-wire compressed schedules vs
    dense (ROADMAP open item 4: the win must be step-time, not
    bytes-accounted): full SSGD training steps at a comm-bound
    geometry, ``ssgd_comm_{int8,topk}_step_speedup`` = compressed
    steps/s ÷ dense steps/s, emitted (like the wire-reduction pair) at
    the canonical :data:`COMM_CANONICAL_SHARDS` geometry, with the
    per-schedule step rates recorded on every multi-shard mesh.

    The int8 schedule also runs its ``@seq`` A/B (the bitwise-identical
    sequential bucket loop) to measure what the double-buffered overlap
    pipeline hides: ``overlap_hidden_ms_per_step`` = sequential −
    overlapped step time, fed into the ``comm.overlap_hidden_ms`` /
    ``comm.sync_ms`` counters that ``tda report`` renders as the
    overlap-efficiency line.

    Honesty note, recorded in the line's ``wire`` field: on a real
    interconnect (TPU ICI/DCN) the sync's wire time is what the int8
    ring cuts 4x and the pipeline hides, so the ratio is the claim; on
    a single-host CPU mesh the "wire" is shared memory — a fused XLA
    AllReduce with no transfer to compress — so quantize/ring work is
    pure overhead there and the measured ratio honestly reads < 1.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpu_distalg.models import ssgd
    from tpu_distalg.parallel import comms, parallelize
    from tpu_distalg.utils import profiling

    n_shards = int(mesh.shape["data"])
    if n_shards < 2:
        return  # no per-step collective exists to re-schedule
    on_tpu = next(iter(mesh.devices.flat)).platform == "tpu"
    rows = rows_per_shard * n_shards
    rng = np.random.default_rng(0)
    X = rng.standard_normal((rows, d)).astype(np.float32)
    y = (X @ rng.standard_normal(d).astype(np.float32) > 0) \
        .astype(np.float32)
    Xs, ys = parallelize(X, mesh), parallelize(y, mesh)
    Xt = jnp.zeros((1, d), jnp.float32)
    yt = jnp.zeros((1,), jnp.float32)
    w0 = jnp.zeros((d,), jnp.float32)

    def rate(sched):
        cfg = ssgd.SSGDConfig(n_iterations=steps, eval_test=False,
                              comm=sched, mini_batch_fraction=1.0)
        fn = ssgd.make_train_fn(mesh, cfg, Xs.n_padded, d=d)
        if sched == "dense":
            timed = lambda: fn(Xs.data, ys.data, Xs.mask,  # noqa: E731
                               Xt, yt, w0)
        else:
            sync = ssgd._comm_sync(mesh, cfg, d)
            res0 = jax.device_put(
                jnp.asarray(sync.init_state()),
                NamedSharding(mesh, P("data", None)))
            timed = lambda: fn(Xs.data, ys.data, Xs.mask,  # noqa: E731
                               Xt, yt, w0, res0)
        best, spread = profiling.steps_per_sec(
            timed, steps=steps, repeats=repeats, with_stats=True)
        return best, spread

    dense_rate, dense_spread = rate("dense")
    wire = ("ici/dcn" if on_tpu
            else "emulated (single-host shared memory — no transfer "
                 "to compress, so compressed schedules read < 1 here; "
                 "the claim geometry is a real interconnect)")
    # topk has no @seq A/B: the single-bucket pipeline is trace-
    # identical either way, so a seq pass would burn a full run to
    # measure jitter and publish it as a calibrated hidden_ms
    for tag, ov_spec, seq_spec in (
            ("int8", "int8", "int8@seq"),
            ("topk", "topk:0.01", None)):
        ov_rate, ov_spread = rate(ov_spec)
        seq_rate = rate(seq_spec)[0] if seq_spec else ov_rate
        # what the double-buffered pipeline hid (vs its bitwise-equal
        # sequential A/B), and the comm time still exposed over dense
        hidden_ms = max(0.0, (1.0 / seq_rate - 1.0 / ov_rate) * 1e3)
        exposed_ms = max(0.0, (1.0 / ov_rate - 1.0 / dense_rate) * 1e3)
        if tag == "int8":
            # the report's overlap-efficiency line describes ONE
            # schedule's pipeline, not a blend: only the multi-bucket
            # int8 ring (the schedule the pipeline exists for) feeds
            # the counters; topk's single pair-buffer A/B is a no-op
            # by construction and is recorded in its line fields only
            comms.emit_overlap_counters(hidden_ms * steps,
                                        exposed_ms * steps)
        line = {
            "metric": f"ssgd_comm_{tag}_step_speedup",
            "value": round(ov_rate / dense_rate, 3),
            "unit": "x",
            "vs_baseline": None,
            "steps_per_sec": round(ov_rate, 2),
            "dense_steps_per_sec": round(dense_rate, 2),
            "sequential_steps_per_sec": round(seq_rate, 2),
            "overlap_hidden_ms_per_step": round(hidden_ms, 3),
            "comm_exposed_ms_per_step": round(exposed_ms, 3),
            "d": d, "rows": rows, "n_shards": n_shards,
            "steps": steps, "wire": wire,
            "dense_spread": dense_spread, "spread": ov_spread,
            "note": "full SSGD steps at a comm-bound geometry "
                    "(4 MB f32 gradient, tiny per-shard matvec); "
                    "measured step time, not byte accounting",
        }
        if n_shards != COMM_CANONICAL_SHARDS:
            # off-geometry meshes still record the measurement, under
            # a shard-count-suffixed name so the canonical claim metric
            # can never be overwritten by another geometry
            line["metric"] += f"_at_{n_shards}shards"
        emit(line)


def _bench_comm_speedup(mesh, n_chips):
    """The measured step-time phase — see
    :func:`run_comm_step_speedup`."""
    run_comm_step_speedup(mesh, _emit)


def _rig_profile():
    """The newest valid RigProfile tagged with THIS rig's hostname, or
    None — read-only (never measures): the init-retry pricing must not
    spend seconds profiling before the backend is even up. The tuned
    A/B phases use :func:`ensure_profile`, which measures on a miss."""
    global _TUNE_PROFILE_ID
    from tpu_distalg import tune as ttune

    try:
        prof, _path = ttune.newest_profile(rig=socket.gethostname())
    except Exception:  # noqa: BLE001 — a bad profile dir never blocks init
        return None
    if prof is not None:
        _TUNE_PROFILE_ID = prof["profile_id"]
    return prof


def ensure_profile(*, backend="cpu", quick=True):
    """The newest rig-matching RigProfile — measured fresh (quick
    pass, no backend-init subprocess) when this rig has none, so the
    tuned A/B phases never resolve geometry from another machine's
    numbers. A freshly measured profile is published to the default
    profile dir (best-effort) so ``--tune auto`` and later rounds
    reuse it."""
    global _TUNE_PROFILE_ID
    from tpu_distalg import tune as ttune

    prof, _path = ttune.newest_profile(rig=socket.gethostname())
    if prof is None:
        meas = ttune.measure_rig(seed=0, quick=quick,
                                 include_backend_init=False)
        prof = ttune.build_profile(meas, created_unix=time.time(),
                                   seed=0, backend=backend)
        try:
            ttune.save_profile(prof)
        except OSError:
            pass  # read-only checkout: the in-memory profile still drives
    _TUNE_PROFILE_ID = prof["profile_id"]
    return prof


def run_tuned_step_speedup(mesh, emit, *, profile=None,
                           d=COMM_SPEEDUP_D,
                           rows_per_shard=COMM_SPEEDUP_ROWS_PER_SHARD,
                           steps=30, repeats=3):
    """MEASURED step-time of the cost-model-resolved comm geometry vs
    the default table (``tuned_step_speedup`` = tuned steps/s ÷
    default steps/s): the autotuner's end-to-end claim, at the same
    comm-bound SSGD geometry as :func:`run_comm_step_speedup`.

    Honesty rules, both directions: when the resolver CHOOSES the
    default schedule (on a single-host rig the device "wire" is shared
    memory — nothing to compress, so the resolver keeps dense), both
    arms would time the SAME compiled program, so the ratio is emitted
    as exactly 1.0 with ``identical_geometry: true`` instead of
    publishing two noise samples of one program as a "speedup"; and
    when the arms DO differ, a measured ratio below 1.0 RAISES (the
    resolver mispredicted on this rig — a recorded phase error the
    cost model must answer for, never a fabricated floor-claim
    number). The default arm's measured step time is recorded as the
    ``tune.measured_step_ms`` gauge either way, so ``tda report`` can
    render predicted-vs-measured."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpu_distalg import tune as ttune
    from tpu_distalg.models import ssgd
    from tpu_distalg.parallel import parallelize
    from tpu_distalg.utils import profiling

    n_shards = int(mesh.shape["data"])
    on_tpu = next(iter(mesh.devices.flat)).platform == "tpu"
    if profile is None:
        profile = ensure_profile(backend="tpu" if on_tpu else "cpu")
    res = ttune.resolve(profile, ttune.Workload(
        d=d, n_workers=n_shards, transport="device",
        n_shards=n_shards))
    default_spec = str(ttune.defaults.DEFAULT_GEOMETRY["comm"])
    tuned_spec = res.comm_string()

    rng = np.random.default_rng(0)
    rows = rows_per_shard * max(1, n_shards)
    X = rng.standard_normal((rows, d)).astype(np.float32)
    y = (X @ rng.standard_normal(d).astype(np.float32) > 0) \
        .astype(np.float32)
    Xs, ys = parallelize(X, mesh), parallelize(y, mesh)
    Xt = jnp.zeros((1, d), jnp.float32)
    yt = jnp.zeros((1,), jnp.float32)
    w0 = jnp.zeros((d,), jnp.float32)

    def rate(sched):
        cfg = ssgd.SSGDConfig(n_iterations=steps, eval_test=False,
                              comm=sched, mini_batch_fraction=1.0)
        fn = ssgd.make_train_fn(mesh, cfg, Xs.n_padded, d=d)
        if sched == "dense":
            timed = lambda: fn(Xs.data, ys.data, Xs.mask,  # noqa: E731
                               Xt, yt, w0)
        else:
            sync = ssgd._comm_sync(mesh, cfg, d)
            res0 = jax.device_put(
                jnp.asarray(sync.init_state()),
                NamedSharding(mesh, P("data", None)))
            timed = lambda: fn(Xs.data, ys.data, Xs.mask,  # noqa: E731
                               Xt, yt, w0, res0)
        return profiling.steps_per_sec(timed, steps=steps,
                                       repeats=repeats,
                                       with_stats=True)

    default_rate, default_spread = rate(default_spec)
    tevents.gauge("tune.measured_step_ms", 1e3 / default_rate)
    line = {
        "metric": "tuned_step_speedup",
        "unit": "x",
        "vs_baseline": None,
        "tune_profile": profile["profile_id"],
        "rig": profile.get("rig"),
        "comm_default": default_spec,
        "comm_tuned": tuned_spec,
        "predicted_sync_ms": res.predicted_sync_ms(),
        "default_steps_per_sec": round(default_rate, 2),
        "d": d, "rows": rows, "n_shards": n_shards, "steps": steps,
    }
    if tuned_spec == default_spec or n_shards < 2:
        emit({**line, "value": 1.0, "identical_geometry": True,
              "steps_per_sec": round(default_rate, 2),
              "note": "resolver chose the default geometry for this "
                      "rig (no device interconnect worth compressing "
                      "for), so both arms are the same compiled "
                      "program — ratio 1.0 by construction, not two "
                      "noise samples"})
        return
    tuned_rate, tuned_spread = rate(tuned_spec)
    speedup = tuned_rate / default_rate
    if speedup < 1.0:
        raise RuntimeError(
            f"resolved geometry ({tuned_spec}) measured SLOWER than "
            f"the default ({tuned_rate:.2f} vs {default_rate:.2f} "
            f"steps/s, {speedup:.3f}x) — the cost model mispredicted "
            f"on this rig; refusing to record a sub-1.0 value under "
            f"a floor-claimed metric")
    emit({**line, "value": round(speedup, 3),
          "identical_geometry": False,
          "steps_per_sec": round(tuned_rate, 2),
          "dense_spread": default_spread, "spread": tuned_spread,
          "note": "full SSGD steps at the comm-bound geometry: "
                  "cost-model-resolved schedule vs the default "
                  "table, measured step time"})


def run_cluster_tuned_push_pull_speedup(emit, *, profile=None,
                                        fast=False):
    """``cluster_tuned_push_pull_speedup`` — the autotuner's claim at
    the CLUSTER tier: median push→commit→pull round trip on an
    otherwise idle single-worker cluster, default geometry vs the
    cost-model-resolved one (host-wire comm schedule, PS shard
    count/mode, pull-refresh cadence), ratio = default p50 ÷ tuned
    p50 (>1 = tuned is faster). When the resolver lands exactly on
    the default table the second arm is skipped and the ratio is 1.0
    with ``identical_geometry: true`` — same program, same honesty
    rule as :func:`run_tuned_step_speedup`. Raises rather than
    fabricating when an arm reports no push/pull timing."""
    import dataclasses
    import tempfile

    from tpu_distalg import cluster as clus
    from tpu_distalg import tune as ttune

    if profile is None:
        profile = ensure_profile()
    task = clus.TrainTask(n_rows=1024 if fast else 4096)
    res = ttune.resolve(profile, ttune.Workload(
        d=task.n_features + 1, n_rows=task.n_rows, n_workers=1,
        transport="host"))
    base = clus.ClusterConfig(
        n_slots=1, n_windows=8 if fast else 16, staleness=2,
        heartbeat_timeout=3.0, train=task)
    tuned_kw = {}
    if res.source("comm") == "resolved":
        tuned_kw["comm"] = res.comm_string()
    for knob in ("ps_shards", "ps_mode", "pull_refresh_windows"):
        if res.source(knob) == "resolved" \
                and res.value(knob) is not None:
            tuned_kw[knob] = res.value(knob)
    tuned_kw = {k: v for k, v in tuned_kw.items()
                if getattr(base, k) != v}

    def p50(cfg, arm):
        with tempfile.TemporaryDirectory(
                prefix=f"tda_tuned_{arm}_") as ckpt:
            r = clus.run_local_cluster(
                dataclasses.replace(cfg, checkpoint_dir=ckpt),
                spawn="thread", timeout=120.0)
        stats = (r["worker_stats"] or {}).get(0) or {}
        v = stats.get("push_pull_ms_p50")
        if not v or not stats.get("pushes"):
            raise RuntimeError(
                f"{arm} arm reported no push/pull timing "
                f"(stats={stats}) — refusing to fabricate a speedup")
        return float(v)

    base_p50 = p50(base, "default")
    line = {
        "metric": "cluster_tuned_push_pull_speedup",
        "unit": "x",
        "vs_baseline": None,
        "tune_profile": profile["profile_id"],
        "rig": profile.get("rig"),
        "default_p50_ms": round(base_p50, 3),
        "tuned_knobs": {k: str(v) for k, v in sorted(
            tuned_kw.items())},
        "n_windows": base.n_windows,
    }
    if not tuned_kw:
        emit({**line, "value": 1.0, "identical_geometry": True,
              "note": "resolver landed on the default table for this "
                      "rig/workload — one arm measured, ratio 1.0 by "
                      "construction"})
        return
    tuned_p50 = p50(dataclasses.replace(
        base, tune_profile=profile["profile_id"], **tuned_kw),
        "tuned")
    emit({**line, "value": round(base_p50 / tuned_p50, 3),
          "identical_geometry": False,
          "tuned_p50_ms": round(tuned_p50, 3),
          "note": "median push->commit->pull round trip on an idle "
                  "single-worker cluster: cost-model-resolved "
                  "geometry vs the default table"})


def _bench_tuned_step(mesh, n_chips):
    """The tuned-geometry step-time A/B — see
    :func:`run_tuned_step_speedup`."""
    run_tuned_step_speedup(mesh, _emit)


def _bench_cluster_tuned(mesh, n_chips):
    """The cluster-tier tuned-geometry A/B — see
    :func:`run_cluster_tuned_push_pull_speedup`."""
    run_cluster_tuned_push_pull_speedup(_emit)


#: canonical device-reshard payload (the metric name carries it)
RESHARD_PAYLOAD_GB = 1.0
#: factor rank of the reshard bench's ALS-shaped tree
RESHARD_RANK = 128


def run_reshard_bench(mesh, emit, *, payload_gb=RESHARD_PAYLOAD_GB,
                      repeats=3):
    """Device-side reshard vs the host gather+re-put A/B it replaced
    (``parallel/partition.py``, in the spirit of arXiv:2112.01075):
    an ALS-shaped factor tree in the ``als_train`` layout — U
    row-sharded over data (~95% of the payload), V model-sharded — is
    re-laid-out to ``als_serve`` (U all-gathers to replicated, V stays)
    as ONE compiled collective program, and the same transition is run
    as the old spelling (``np.asarray`` every leaf to this host, then
    ``device_put`` back). ``reshard_1gb_gbps`` = payload GB ÷ device
    reshard seconds at the canonical 1 GB payload (off-canonical
    payloads emit under a suffixed name); the line records the host
    A/B rate, the speedup, and the engine's wire-byte accounting.

    Honesty (the PR 6 convention, in the ``wire`` field): on a
    single-host CPU mesh both paths move host RAM — there is no PCIe
    to skip and no interconnect to ride, so the measured gap is
    scheduling overhead only; the claim geometry is a real TPU, where
    the host path serializes 2×payload over PCIe per leaf and the
    device path moves only the accounted collective bytes."""
    import jax
    import numpy as np

    from tpu_distalg.parallel import partition

    on_tpu = next(iter(mesh.devices.flat)).platform == "tpu"
    k = RESHARD_RANK
    total = payload_gb * 1e9
    n_data = int(mesh.shape["data"])
    n_model = int(mesh.shape["model"])
    # row counts padded to the sharded-axis sizes (the same padding
    # convention the real seams follow)
    u_rows = -(-max(n_data, int(total * 0.95 / (4 * k)))
               // n_data) * n_data
    v_rows = -(-max(n_model, int(total * 0.05 / (4 * k)))
               // n_model) * n_model
    rng = np.random.default_rng(0)
    # dtype=f32 at generation: an .astype copy would transiently hold
    # ~3x the canonical 1 GB payload in host RAM before timing starts
    tree = {"U": rng.standard_normal((u_rows, k), dtype=np.float32),
            "V": rng.standard_normal((v_rows, k), dtype=np.float32)}
    placed = partition.place(tree, "als_train", mesh)
    st = partition.reshard_stats(placed, "als_train", "als_serve",
                                 mesh)
    gb = st["bytes_logical"] / 1e9

    def dev_once():
        out = partition.reshard(placed, "als_train", "als_serve",
                                mesh, emit=False)
        return jax.block_until_ready(out)

    def host_once():
        out = partition.host_gather_reshard(placed, "als_serve", mesh)
        return jax.block_until_ready(out)

    def timed(fn):
        t0 = time.perf_counter()
        fn()
        return time.perf_counter() - t0

    dev_once()  # compile/warm both paths outside the timed region
    host_once()
    t_dev = min(timed(dev_once) for _ in range(repeats))
    t_host = min(timed(host_once) for _ in range(repeats))
    partition.emit_reshard_counters(st)
    line = {
        "metric": "reshard_1gb_gbps",
        "value": round(gb / t_dev, 3),
        "unit": "GB/s",
        "vs_baseline": None,
        "host_gather_gbps": round(gb / t_host, 3),
        "speedup_vs_host": round(t_host / t_dev, 2),
        "payload_gb": round(gb, 3),
        "bytes_wire": st["bytes_wire"],
        "bytes_host_roundtrip": st["bytes_host_roundtrip"],
        "n_shards": int(mesh.shape["data"]),
        "n_model": int(mesh.shape["model"]),
        "wire": ("ici/dcn + pcie A/B" if on_tpu
                 else "emulated (single-host shared memory: both "
                      "paths move host RAM, the gap is scheduling "
                      "only; the claim geometry is a real TPU)"),
        "note": "device reshard als_train->als_serve vs host "
                "gather+re-put of the same tree (bitwise-equal "
                "outputs, pinned in tests/test_partition.py)",
    }
    if abs(payload_gb - RESHARD_PAYLOAD_GB) > 1e-9:
        # off-canonical payloads must not overwrite the claim metric
        line["metric"] += f"_at_{payload_gb:g}gb"
        line["degraded_geometry"] = True
    emit(line)


def _bench_reshard(mesh, n_chips):
    run_reshard_bench(mesh, _emit)


#: the 2-D mesh speedup's comm-bound task geometry: a wide feature dim
#: makes the per-step gradient combine the dominant cost, which is
#: exactly what the model axis divides
MESH2D_D = 8192
MESH2D_ROWS_PER_DEV = 512


def run_mesh2d_bench(mesh, emit, *, d=MESH2D_D,
                     rows_per_dev=MESH2D_ROWS_PER_DEV, steps=30,
                     repeats=3):
    """Full SSGD step time, pure-dp 1-D mesh vs the 2-D data×model
    mesh at the SAME device count — the rule-table unlock measured:
    ``--mesh-shape NxM`` engages the ``ssgd_tp`` table (feature dim
    sharded over the model axis), so each gradient combine moves
    ``d/M`` floats over a ``N``-way ring instead of ``d`` over an
    ``N·M``-way one — 2-D HIERARCHICAL combine falling out of the
    placement, not a hand-written code path.

    ``ssgd_2d_mesh_step_speedup`` = 2-D steps/s ÷ 1-D steps/s at the
    canonical 4-device geometry (2×2 vs 4×1); other device counts
    emit under a device-suffixed name. Honest on host meshes (the
    ``wire`` field): with no real interconnect the combine is a
    shared-memory reduction and the tp split's extra pack/unpack
    reads < 1 here — the claim geometry is a multi-chip mesh."""
    import numpy as np

    from tpu_distalg.models import ssgd
    from tpu_distalg.parallel import get_mesh
    from tpu_distalg.utils import profiling

    devices = list(mesh.devices.flat)
    n = len(devices)
    if n < 4 or n % 2:
        # a claim-registered metric must never just vanish: the raise
        # lands as a RECORDED phase error under _phase_optional (the
        # serve-round-3 convention), naming why this round has no line
        raise RuntimeError(
            f"mesh2d needs >= 4 devices and an even count for the "
            f"2-D split (have {n}) — no ssgd_2d_mesh_step_speedup "
            f"line this round")
    on_tpu = devices[0].platform == "tpu"
    rows = rows_per_dev * n
    rng = np.random.default_rng(0)
    X = rng.standard_normal((rows, d)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    Xt = np.zeros((8, d), np.float32)
    yt = np.zeros((8,), np.float32)
    cfg = ssgd.SSGDConfig(n_iterations=steps, sampler="fused_gather",
                          mini_batch_fraction=1.0)

    def rate(mesh_arm, feature_sharded):
        import dataclasses

        c = dataclasses.replace(cfg, feature_sharded=feature_sharded)
        if feature_sharded:
            fn, X2, w0, meta = ssgd.prepare_fused_tp(X, y, mesh_arm, c)
            X_te = ssgd.tp_augment_test_matrix(Xt, meta)
        else:
            fn, X2, w0, meta = ssgd.prepare_fused(X, y, mesh_arm, c)
            X_te = np.pad(Xt, ((0, 0), (0, meta["d_total"] - d)))
        dummy = np.zeros((1,), np.float32)
        return profiling.steps_per_sec(
            lambda: fn(X2, dummy, dummy, X_te, yt, w0),
            steps=steps, repeats=repeats)

    mesh_1d = get_mesh(data=n, devices=devices)
    mesh_2d = get_mesh(data=n // 2, model=2, devices=devices)
    rate_1d = rate(mesh_1d, False)
    rate_2d = rate(mesh_2d, True)
    line = {
        "metric": "ssgd_2d_mesh_step_speedup",
        "value": round(rate_2d / rate_1d, 3),
        "unit": "x",
        "vs_baseline": None,
        "steps_per_sec_2d": round(rate_2d, 2),
        "steps_per_sec_1d": round(rate_1d, 2),
        "mesh_2d": f"{n // 2}x2", "mesh_1d": f"{n}x1",
        "d": d, "rows": rows, "steps": steps,
        "wire": ("ici/dcn" if on_tpu
                 else "emulated (single-host shared memory — no wire "
                      "for the model axis to divide, so the tp "
                      "split's pack overhead reads < 1 here; the "
                      "claim geometry is a multi-chip mesh)"),
        "note": "fused_gather SSGD, 1-D data mesh vs 2-D data x model "
                "via the ssgd_tp rule table (--mesh-shape config)",
    }
    if n != 4:
        # the canonical claim metric is pinned to the 4-device
        # geometry; other counts record under a suffixed name
        line["metric"] += f"_at_{n}dev"
    if d != MESH2D_D or rows_per_dev != MESH2D_ROWS_PER_DEV:
        # a scaled-down task (the cpu-fallback arm) must not feed the
        # canonical claim metric either — same convention as the
        # reshard payload and closure V checks
        line["metric"] += f"_at_{d}d"
        line["degraded_geometry"] = True
    emit(line)


def _bench_mesh2d(mesh, n_chips):
    run_mesh2d_bench(mesh, _emit)


#: closure-at-scale task: a forward random DAG (every vertex gets
#: ``deg`` random forward edges) — small diameter (the naive re-join
#: converges in ~log rounds), closure ~0.5·V² pairs, so ≥10⁷ paths at
#: the canonical geometry without a V-round chain walk
CLOSURE_V = 6200
CLOSURE_DEG = 8
#: the claim floor the canonical graph must clear (VERDICT advice #8)
CLOSURE_MIN_PATHS = 10_000_000


def closure_dag_edges(V: int, deg: int, seed: int = 0):
    """The bench's forward-random-DAG edge list (dedup'd)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    src = np.repeat(np.arange(V - 1), deg)
    span = V - 1 - src
    dst = src + 1 + (rng.random(len(src)) * span).astype(np.int64)
    return np.unique(np.stack([src, dst], 1), axis=0)


def closure_host_count(V: int, edges) -> int:
    """Exact closure size by reverse-topological bitset DP on the host
    — O(E·V/64) word ops (~5M for the canonical graph), so the bench
    can assert the sparse engine's count EXACTLY at full scale, not
    just at the small parity scale."""
    import numpy as np

    adj: list[list[int]] = [[] for _ in range(V)]
    for s, dd in edges:
        adj[int(s)].append(int(dd))
    words = (V + 63) // 64
    reach = np.zeros((V, words), np.uint64)
    total = 0
    for i in range(V - 1, -1, -1):
        for j in adj[i]:
            reach[i] |= reach[j]
            reach[i, j // 64] |= np.uint64(1 << (j % 64))
        total += int(np.bitwise_count(reach[i]).sum()) \
            if hasattr(np, "bitwise_count") else sum(
                bin(int(w)).count("1") for w in reach[i])
    return total


def run_closure_bench(mesh, emit, *, V=CLOSURE_V, deg=CLOSURE_DEG,
                      min_paths=CLOSURE_MIN_PATHS):
    """The sparse transitive-closure scale story (VERDICT advice #8):

      1. PARITY — at an overlapping small scale (V=120) the sparse
         path's pair set must equal the dense MXU oracle's exactly;
         a mismatch RAISES (the phase is ``_phase_optional``, so a
         failure is recorded, never emitted as a fabricated rate).
      2. SCALE — a graph whose closure the host bitset DP proves
         ≥ ``min_paths`` (10⁷ canonical) runs through
         ``run_sparse_auto`` (capacity auto-sizing with the
         documented over-budget refusal); the engine's count must
         equal the DP count EXACTLY, and the line reports end-to-end
         paths/second including any capacity regrowth.

    Off-canonical (smaller) geometries emit under a V-suffixed name
    with ``degraded_geometry`` set, so the canonical claim metric is
    never overwritten by a host-mesh run."""
    import time

    import numpy as np

    from tpu_distalg.models import transitive_closure as tc

    # 1. parity vs the dense oracle at overlapping scale
    Vp = 120
    pe = closure_dag_edges(Vp, 5, seed=1)
    dense = tc.run(pe, mesh, n_vertices=Vp)
    sparse_small = tc.run_sparse_auto(pe, mesh, n_vertices=Vp)
    dm = np.asarray(dense.paths)[:Vp, :Vp]
    dset = set(zip(*np.nonzero(dm)))
    sset = set(map(tuple, sparse_small.paths))
    if dset != sset:
        raise AssertionError(
            f"sparse closure diverged from the dense oracle at "
            f"V={Vp}: {len(sset)} vs {dense.n_paths} paths")

    # 2. the ≥10⁷-path scale line, count pinned to the host DP
    edges = closure_dag_edges(V, deg, seed=0)
    want = closure_host_count(V, edges)
    if V >= CLOSURE_V and want < min_paths:
        raise AssertionError(
            f"closure task too small: {want} < {min_paths} paths — "
            f"grow CLOSURE_V")
    t0 = time.perf_counter()
    res = tc.run_sparse_auto(
        edges, mesh, n_vertices=V,
        # the host DP already proved the size — start the buffer
        # there (auto-growth stays the safety net for graphs without
        # a pre-count, and is itself pinned in tests/test_partition)
        start_capacity=int(want * 1.1))
    dt = time.perf_counter() - t0
    if res.n_paths != want:
        raise AssertionError(
            f"sparse closure count {res.n_paths} != host DP {want}")
    line = {
        "metric": "closure_10m_paths_per_sec",
        "value": round(res.n_paths / dt, 1),
        "unit": "paths/s",
        "vs_baseline": None,
        "n_paths": res.n_paths, "n_vertices": V,
        "n_edges": int(len(edges)), "n_rounds": res.n_rounds,
        "seconds": round(dt, 2),
        "note": "forward-random-DAG closure via run_sparse_auto "
                "(capacity auto-sized; count == host bitset-DP "
                "exact; parity vs the dense oracle asserted at "
                "overlapping scale)",
    }
    if V < CLOSURE_V:
        line["metric"] += f"_at_{V}v"
        line["degraded_geometry"] = True
    emit(line)


def _bench_closure(mesh, n_chips):
    run_closure_bench(mesh, _emit)


#: the canonical seeded straggler plan the SSP headline is pinned to:
#: each (tick, shard) cell independently straggles with p=0.25, paying
#: SSP_STRAGGLE_UNITS of injected interference compute (real FLOPs
#: inside the program — ssp.straggle_work); the plan string is recorded
#: in the bench line so the number replays from its inputs
SSP_STRAGGLE_UNITS = 800
SSP_STRAGGLE_PLAN = (
    f"seed=7;shard:straggle@p0.25=straggle:{SSP_STRAGGLE_UNITS}")
#: staleness bound of the canonical SSP measurement (ticks per window)
SSP_STALENESS = 8
#: convergence-band width for the equal-loss comparison (accuracy
#: points below the BSP endpoint that still count as "reached")
SSP_CONV_BAND = 0.01


def run_ssp_straggler_speedup(mesh, emit, *, steps=64, repeats=3,
                              conv_iters=600, staleness=None):
    """The SSP headline pair (ROADMAP item 2's acceptance evidence),
    shared by the bench ``ssp`` phase and the CPU-fallback tier:

    ``ssgd_ssp_straggler_speedup`` — FULL measured step time, BSP vs
    SSP, under the canonical seeded straggler plan at the canonical
    :data:`COMM_CANONICAL_SHARDS` geometry (the ``run_comm_step_speedup``
    shape). Both arms pay the identical compiled-in interference
    schedule; BSP's per-tick psum barrier serializes every shard's
    delay while SSP's window structure overlaps them — the ratio is
    the stall time the bounded-staleness layer removes, measured, not
    accounted. Unlike the comm-compression lines, this one is honest
    ON a host mesh too: the straggle delay is real compute on the
    straggling device-thread, and the BSP barrier really waits for it.

    ``ssgd_ssp_equal_loss_steps`` — the convergence cost of the
    asynchrony: steps SSP needs to reach the BSP endpoint accuracy
    minus :data:`SSP_CONV_BAND` on the converging comm-comparison
    task, as a ratio of BSP's own steps-to-target (SSP evaluates at
    window boundaries, so its step count is window-quantized).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpu_distalg import faults as tfaults
    from tpu_distalg.models import ssgd
    from tpu_distalg.parallel import parallelize
    from tpu_distalg.parallel import ssp as pssp
    from tpu_distalg.utils import profiling

    n_shards = int(mesh.shape["data"])
    if n_shards < 2:
        return  # no barrier exists for a straggler to serialize
    s_bound = staleness or SSP_STALENESS
    # the PR 6 convention, extended: the canonical claim names are
    # reserved for the canonical (shard count, staleness bound)
    # geometry — any other measurement records under a suffixed name
    # so it can never overwrite the claims/tripwire reference
    name_suffix = ""
    if n_shards != COMM_CANONICAL_SHARDS:
        name_suffix += f"_at_{n_shards}shards"
    if s_bound != SSP_STALENESS:
        name_suffix += f"_bound{s_bound}"
    plan = tfaults.FaultPlan.parse(SSP_STRAGGLE_PLAN)
    sync_spelling = f"ssp:{s_bound}"
    X, y, X_te, y_te = comm_comparison_task()
    d = X.shape[1]
    Xs, ys = parallelize(X, mesh), parallelize(y, mesh)
    dummy_te = (jnp.zeros((1, d), jnp.float32),
                jnp.zeros((1,), jnp.float32))
    w0 = jnp.zeros((d,), jnp.float32)
    n_win, padded = pssp.window_grid(steps, s_bound)
    extra = pssp.compile_straggle_schedule(padded, n_shards, plan=plan)
    extra[steps:] = 0  # pad ticks don't exist (mirrors _train_ssp):
    # neither interference nor boundary-busy may leak from the padding
    # of a non-divisible off-canonical bound

    # -- BSP arm: the classic per-tick psum trainer + the schedule --
    cfg = ssgd.SSGDConfig(n_iterations=steps, eval_test=False)
    bsp_fn = ssgd.make_bsp_straggler_fn(mesh, cfg, Xs.n_padded, extra)
    bsp_rate, bsp_spread = profiling.steps_per_sec(
        lambda: bsp_fn(Xs.data, ys.data, Xs.mask, *dummy_te, w0),
        steps=steps, repeats=repeats, with_stats=True)

    # -- SSP arm: same schedule, merges once per window --
    cfg_ssp = ssgd.SSGDConfig(n_iterations=steps, eval_test=False,
                              sync=sync_spelling)
    ssp_fn = ssgd.make_ssp_train_fn(
        mesh, cfg_ssp, Xs.n_padded, d,
        active=(True,) * n_shards, n_win_seg=n_win,
        total_ticks=steps)
    # the carry comes from the trainer's own init helper — the bench
    # measures the state layout the trainer actually ships
    _, clocks0, pend0, basegen0, wl0, accd0, res0 = \
        ssgd.ssp_init_state(mesh, cfg_ssp, d, w=np.asarray(w0))
    shard2 = NamedSharding(mesh, P("data", None))
    wl0 = jax.device_put(jnp.asarray(wl0), shard2)
    accd0 = jax.device_put(jnp.asarray(accd0), shard2)
    res0 = jax.device_put(jnp.asarray(res0), shard2)
    clocks0, pend0, basegen0 = (jnp.asarray(clocks0),
                                jnp.asarray(pend0),
                                jnp.asarray(basegen0))
    extra_seg = jnp.asarray(extra.reshape(n_win, s_bound, n_shards))
    ssp_rate, ssp_spread = profiling.steps_per_sec(
        lambda: ssp_fn(Xs.data, ys.data, Xs.mask, *dummy_te, w0,
                       clocks0, pend0, basegen0, wl0, accd0, res0,
                       extra_seg, jnp.int32(0)),
        steps=steps, repeats=repeats, with_stats=True)

    pssp.emit_stall_avoided(steps / bsp_rate, steps / ssp_rate, steps)
    line = {
        "metric": "ssgd_ssp_straggler_speedup",
        "value": round(ssp_rate / bsp_rate, 3),
        "unit": "x",
        "vs_baseline": None,
        "ssp_steps_per_sec": round(ssp_rate, 2),
        "bsp_steps_per_sec": round(bsp_rate, 2),
        "staleness_bound": s_bound,
        "straggle_plan": SSP_STRAGGLE_PLAN,
        "straggled_cells": int(np.count_nonzero(extra)),
        "steps": steps, "n_shards": n_shards,
        "bsp_spread": bsp_spread, "spread": ssp_spread,
        "note": "full measured step time under the SAME compiled-in "
                "seeded interference schedule; BSP's per-tick barrier "
                "pays every shard's delay serially, SSP's window "
                "overlaps them — real on host meshes too (the delay "
                "is real compute, the barrier really waits)",
    }
    line["metric"] += name_suffix
    emit(line)

    # -- convergence: steps to the BSP endpoint band (no faults) --
    conv_bsp = ssgd.SSGDConfig(n_iterations=conv_iters)
    bsp_res = ssgd.train(X, y, X_te, y_te, mesh, conv_bsp)
    conv_ssp = ssgd.SSGDConfig(n_iterations=conv_iters,
                               sync=sync_spelling)
    ssp_res = ssgd.train(X, y, X_te, y_te, mesh, conv_ssp)
    bsp_accs = np.asarray(bsp_res.accs)
    ssp_accs = np.asarray(ssp_res.accs)
    target = float(bsp_accs[-1]) - SSP_CONV_BAND

    def first_reach(accs):
        idx = np.nonzero(accs >= target)[0]
        return int(idx[0]) + 1 if idx.size else None

    bsp_steps = first_reach(bsp_accs) or conv_iters
    ssp_steps = first_reach(ssp_accs)
    if ssp_steps is None:
        # the serve-phase lesson (round 13, review round 3): a
        # fabricated 0.0 would read as PERFECT to the lower-is-better
        # tripwire and the ceiling claim, and poison the reference —
        # raise (the phase is optional) instead of emitting
        raise RuntimeError(
            f"ssp never reached the BSP band (target {target:.4f}, "
            f"ssp final {float(ssp_accs[-1]):.4f}) in {conv_iters} "
            f"steps — investigate before a ratio can be claimed")
    ratio = ssp_steps / bsp_steps
    line = {
        "metric": "ssgd_ssp_equal_loss_steps",
        "value": round(ratio, 3),
        "unit": "x",
        "vs_baseline": None,
        "target_acc": round(target, 6),
        "bsp_final_acc": round(float(bsp_accs[-1]), 6),
        "ssp_final_acc": round(float(ssp_accs[-1]), 6),
        "bsp_steps_to_target": bsp_steps,
        "ssp_steps_to_target": ssp_steps,
        "staleness_bound": s_bound,
        "n_iterations": conv_iters, "n_shards": n_shards,
        "note": "steps to reach (BSP endpoint − band) as a ratio of "
                "BSP's own; SSP evaluates at window boundaries, so "
                "its count is window-quantized; faults-free run — the "
                "straggled-convergence evidence is tda chaos "
                "--workload ssp",
    }
    line["metric"] += name_suffix
    emit(line)


#: the canonical cluster bench geometry: 3 worker slots, one seeded
#: kill mid-run — the elastic-vs-restart A/B and the replay tests pin
#: to these numbers
CLUSTER_SLOTS = 3
CLUSTER_KILL_SLOT = 1

#: the canonical cluster WIRE schedule the measured arms run under
#: (the TCP bytes are real, so — unlike the host-shared-memory CPU
#: meshes of the in-process comm lines, PR 6's caveat — the
#: compression win is honestly measurable here)
CLUSTER_BENCH_COMM = "int8:5"


def run_cluster_bench(emit, *, fast: bool = False):
    """The multi-process elastic runtime's headline pair
    (tpu_distalg/cluster/), shared by the bench ``cluster`` phase and
    the CPU-fallback tier (the cluster runs on host processes/threads
    by construction — no TPU dependency, honest everywhere):

    ``ssgd_cluster_elastic_speedup`` — FULL measured wall clock of a
    3-worker local cluster run that loses one worker to a seeded
    ``kill -9`` mid-run, ELASTIC policy (training continues at
    reduced quorum, the replacement rejoins by pulling the center) vs
    the RESTART-policy baseline (any death aborts; the whole cluster
    respawns from the durable checkpoint — the gang-scheduled
    BSP-restart world the reference's process model lives in). Same
    plan, same task, same thread-mode workers in both arms, so the
    ratio isolates the failure-handling policy: the baseline re-pays
    the respawn plus every window since the last checkpoint.

    ``cluster_push_pull_ms`` — median measured push→commit→pull round
    trip at the PS tier on an otherwise idle single-worker cluster
    (framed delta up, merge, framed center back): the transport +
    merge cost floor every window pays.

    ``cluster_coordinator_recovery_ms`` — median measured
    detect→recover→first-recommitted-window latency when the
    COORDINATOR is killed mid-window by a seeded
    ``cluster:coordinator`` plan: the launcher respawns it on the
    same port, it replays the durable WAL on top of the newest center
    checkpoint, the surviving workers reconnect + re-push, and the
    clock stamps at the first post-recovery commit. The same run's
    final center is asserted BITWISE-identical to an undisturbed
    run's (recovery must not tax correctness), and the elastic-
    speedup arm above re-runs every round to show the WAL doesn't tax
    the no-fault path.

    All three RAISE instead of emitting fabricated values when a run
    fails to complete or a scheduled fault never fires (the
    serve-round-3 lesson: a fabricated number poisons the tripwire
    reference).
    """
    import dataclasses
    import tempfile

    import numpy as _np

    from tpu_distalg import cluster as clus

    windows = 8 if fast else 24
    s = 2 if fast else 4
    ce = 3 if fast else 8
    kill_w = windows // 2
    hit = kill_w * CLUSTER_SLOTS + CLUSTER_KILL_SLOT
    plan = f"seed=7;cluster:worker@{hit}=kill"
    task = clus.TrainTask(n_rows=1024 if fast else 4096)
    base = clus.ClusterConfig(
        n_slots=CLUSTER_SLOTS, n_windows=windows, staleness=s,
        heartbeat_timeout=3.0, plan_spec=plan, train=task,
        comm=CLUSTER_BENCH_COMM, checkpoint_every=ce)

    # BOTH arms pay the same periodic checkpoint cadence — the ratio
    # must isolate the failure POLICY, not gift the elastic arm the
    # restart arm's checkpoint I/O
    with tempfile.TemporaryDirectory(prefix="tda_cluster_e_") as d:
        res_e = clus.run_local_cluster(
            dataclasses.replace(base, checkpoint_dir=d),
            spawn="thread", timeout=300.0)
    with tempfile.TemporaryDirectory(prefix="tda_cluster_r_") as d:
        res_r = clus.run_local_cluster(
            dataclasses.replace(base, policy="restart",
                                checkpoint_dir=d),
            spawn="thread", timeout=300.0)
    for name, res in (("elastic", res_e), ("restart", res_r)):
        if res["version"] != windows:
            raise RuntimeError(
                f"cluster {name} arm stopped at window "
                f"{res['version']}/{windows} — no speedup can be "
                f"claimed from an incomplete run")
    if res_r["restarts"] < 1 or res_e["respawns"] < 1:
        raise RuntimeError(
            f"the seeded kill never fired (restarts="
            f"{res_r['restarts']}, respawns={res_e['respawns']}) — "
            f"the A/B would compare two undisturbed runs")
    speedup = res_r["wall_seconds"] / res_e["wall_seconds"]
    emit({
        "metric": "ssgd_cluster_elastic_speedup",
        "value": round(speedup, 3),
        "unit": "x",
        "vs_baseline": None,
        "elastic_wall_s": res_e["wall_seconds"],
        "restart_wall_s": res_r["wall_seconds"],
        "elastic_final_acc": round(res_e["accuracy"], 6),
        "restart_final_acc": round(res_r["accuracy"], 6),
        "n_workers": CLUSTER_SLOTS, "n_windows": windows,
        "staleness": s, "kill_window": kill_w,
        "checkpoint_every": ce, "plan": plan,
        "comm": CLUSTER_BENCH_COMM,
        "note": "wall clock, kill-one-worker mid-run: elastic "
                "(continue at reduced quorum + rejoin from the "
                "center) vs restart-policy baseline (abort + full "
                "respawn from the checkpoint); thread-mode workers "
                "in both arms under the compressed wire, so the "
                "ratio isolates the policy",
    })

    cfg_p = clus.ClusterConfig(
        n_slots=1, n_windows=8 if fast else 16, staleness=2,
        heartbeat_timeout=3.0, comm=CLUSTER_BENCH_COMM, train=task)
    res_p = clus.run_local_cluster(cfg_p, spawn="thread",
                                   timeout=120.0)
    stats = (res_p["worker_stats"] or {}).get(0) or {}
    p50 = stats.get("push_pull_ms_p50")
    if not p50 or not stats.get("pushes"):
        raise RuntimeError(
            f"push/pull timing never reported (stats={stats}) — "
            f"refusing to fabricate a latency")
    emit({
        "metric": "cluster_push_pull_ms",
        "value": round(float(p50), 3),
        "unit": "ms",
        "vs_baseline": None,
        "pushes": stats["pushes"],
        "mean_ms": round(stats["push_pull_ms_total"]
                         / max(1, stats["pushes"]), 3),
        "comm": CLUSTER_BENCH_COMM,
        "note": "median push->commit->pull round trip at the PS tier "
                "(compressed delta up, exact decode + staleness-"
                "weighted merge, compressed version-delta pull back) "
                "on an idle single-worker cluster — the per-window "
                "transport+merge cost floor; measured inside the "
                "async sender, so the overlapped compute never "
                "deflates it",
    })

    # coordinator crash tolerance: kill the CONTROL PLANE mid-window
    # (seeded cluster:coordinator plan), measure detect -> WAL replay
    # -> worker reconnects -> first recommitted window, over several
    # kills for a median. The recovered run must be BITWISE-identical
    # to the undisturbed elastic arm above (same task, no worker
    # faults) — recovery that taxes correctness is not recovery.
    kills = 2 if fast else 5
    rec_ms: list = []
    kill_centers: list = []
    for k in range(kills):
        coord_w = windows // 2
        plan_c = f"seed={11 + k};cluster:coordinator@{coord_w}=kill"
        with tempfile.TemporaryDirectory(
                prefix="tda_cluster_c_") as d:
            res_c = clus.run_local_cluster(
                clus.ClusterConfig(
                    n_slots=CLUSTER_SLOTS, n_windows=windows,
                    # generous: a loaded box must not flip a slow
                    # reconnect into a readmission (a legitimate
                    # degraded path that would fail the bitwise
                    # acceptance below for the wrong reason)
                    staleness=s, heartbeat_timeout=15.0,
                    plan_spec=plan_c, train=task,
                    comm=CLUSTER_BENCH_COMM,
                    checkpoint_every=ce, checkpoint_dir=d),
                spawn="thread", timeout=300.0)
        if res_c["version"] != windows:
            raise RuntimeError(
                f"coordinator-kill run {k} stopped at window "
                f"{res_c['version']}/{windows} — recovery failed, "
                f"no latency can be claimed")
        if res_c["coordinator_recoveries"] != 1 or \
                not res_c["recovery_ms"]:
            raise RuntimeError(
                f"the seeded coordinator kill never fired or was "
                f"never measured (recoveries="
                f"{res_c['coordinator_recoveries']}, recovery_ms="
                f"{res_c['recovery_ms']}) — refusing to fabricate "
                f"a recovery latency")
        rec_ms.extend(res_c["recovery_ms"])
        kill_centers.append(res_c["center"]["w"])
    # bitwise acceptance vs an undisturbed run of the same config —
    # EVERY kill run's center, not just the last one's (a divergence
    # in any run must not ship inside the median)
    res_u = clus.run_local_cluster(
        clus.ClusterConfig(
            n_slots=CLUSTER_SLOTS, n_windows=windows, staleness=s,
            heartbeat_timeout=3.0, comm=CLUSTER_BENCH_COMM,
            train=task),
        spawn="thread", timeout=300.0)
    for k, center in enumerate(kill_centers):
        if not _np.array_equal(center, res_u["center"]["w"]):
            raise RuntimeError(
                f"recovered center of kill run {k} diverged from "
                f"the undisturbed run — the WAL replay/rollback "
                f"contract is broken; refusing to emit a recovery "
                f"latency for an incorrect recovery")
    emit({
        "metric": "cluster_coordinator_recovery_ms",
        "value": round(float(_np.percentile(rec_ms, 50)), 3),
        "unit": "ms",
        "vs_baseline": None,
        "kills": kills,
        "recovery_ms_all": [round(float(x), 3) for x in rec_ms],
        "wal_records_replayed": res_c["wal_records_replayed"],
        "bitwise_vs_undisturbed": True,
        "comm": CLUSTER_BENCH_COMM,
        "note": "median detect->recover->first-recommitted-window "
                "after a seeded kill of the coordinator mid-window: "
                "launcher respawn on the same port + WAL replay over "
                "the newest durable center + worker reconnect/"
                "re-push, all under the compressed wire; final "
                "center bitwise-identical to the undisturbed run "
                "(asserted, not assumed)",
    })

    run_cluster_wire_bench(emit, fast=fast)
    if not fast:
        # off-canonical variant: the sparse pair wire, suffixed so
        # the canonical int8 claim metric never ingests it (TDA102
        # names stay bijective with emission sites)
        run_cluster_wire_bench(emit, fast=fast, comm="topk:0.05")


def run_cluster_wire_bench(emit, *, fast: bool = False,
                           comm: str = CLUSTER_BENCH_COMM,
                           workers: int = CLUSTER_SLOTS):
    """``cluster_wire_reduction_vs_dense`` — MEASURED frame bytes of
    the cluster's hot-path traffic (push frames up, center/pull
    frames down, counted by ``transport.wire_stats`` as the encoded
    frames leave for the socket) for a dense run vs a compressed run
    of the same geometry and task. TCP is a real wire, so unlike the
    host-shared-memory CPU-mesh comm lines (PR 6's caveat) this
    ratio is honest on every backend. The compressed arm must also
    CONVERGE: its final accuracy is required inside the SSP chaos
    band of the dense arm's, or the metric raises — a byte ratio
    bought with a broken model is not a win. Off-canonical ``comm``/
    ``workers`` record under suffixed metric names."""
    import dataclasses as _dc

    from tpu_distalg import cluster as clus
    from tpu_distalg.cluster import transport as ctransport
    from tpu_distalg.faults.chaos import SSP_CHAOS_ACC_BAND
    from tpu_distalg.parallel import comms as pcomms

    windows = 4 if fast else 8
    # a model wide enough that the frame HEADER (a few hundred JSON
    # bytes) cannot mask the payload ratio — the claim is about the
    # wire, not the envelope
    d = 2048 if fast else 8192
    task = clus.TrainTask(n_rows=512 if fast else 1024,
                          test_rows=256 if fast else 512,
                          n_features=d)
    base = clus.ClusterConfig(
        n_slots=workers, n_windows=windows, staleness=2,
        heartbeat_timeout=10.0, train=task)

    def arm(comm_spec):
        ctransport.wire_stats_reset()
        res = clus.run_local_cluster(
            _dc.replace(base, comm=comm_spec), spawn="thread",
            timeout=300.0)
        stats = ctransport.wire_stats()
        if res["version"] != windows:
            raise RuntimeError(
                f"wire bench arm {comm_spec!r} stopped at window "
                f"{res['version']}/{windows} — refusing to compare "
                f"bytes of an incomplete run")
        push = stats.get("push", {"frames": 0, "bytes": 0})
        pull = stats.get("center", {"frames": 0, "bytes": 0})
        if not push["bytes"] or not pull["bytes"]:
            raise RuntimeError(
                f"wire bench arm {comm_spec!r} measured no push/pull "
                f"frames ({stats}) — the accounting is broken, "
                f"refusing to fabricate a ratio")
        return res, push, pull

    res_d, push_d, pull_d = arm("dense")
    res_c, push_c, pull_c = arm(comm)
    band = abs(res_c["accuracy"] - res_d["accuracy"])
    if band > SSP_CHAOS_ACC_BAND:
        raise RuntimeError(
            f"compressed arm {comm!r} converged {band:.4f} away from "
            f"dense (band {SSP_CHAOS_ACC_BAND}) — a wire ratio from "
            f"a diverged model is not claimable")
    total_d = push_d["bytes"] + pull_d["bytes"]
    total_c = push_c["bytes"] + pull_c["bytes"]
    sched = pcomms.CommSpec.parse(comm).schedule
    name_suffix = "" if (sched == "int8" and workers == CLUSTER_SLOTS) \
        else f"_{sched}" + ("" if workers == CLUSTER_SLOTS
                            else f"_w{workers}")
    line = {
        "metric": "cluster_wire_reduction_vs_dense",
        "value": round(total_d / total_c, 3),
        "unit": "x",
        "vs_baseline": None,
        "comm": comm,
        "push_reduction": round(push_d["bytes"] / push_c["bytes"], 3),
        "pull_reduction": round(pull_d["bytes"] / pull_c["bytes"], 3),
        "dense_bytes": total_d,
        "compressed_bytes": total_c,
        "push_frames": push_c["frames"],
        "pull_frames": pull_c["frames"],
        "n_workers": workers, "n_windows": windows,
        "n_features": d,
        "acc_dense": round(res_d["accuracy"], 6),
        "acc_compressed": round(res_c["accuracy"], 6),
        "note": "measured frame bytes (push up + center/pull down) "
                "over a full thread-mode cluster run, dense vs "
                "compressed wire at the same geometry/task; "
                "convergence inside the SSP chaos band is asserted, "
                "not assumed",
    }
    line["metric"] += name_suffix
    emit(line)


def run_rowstore_bench(emit, *, fast: bool = False):
    """The sharded row store's headline pair (cluster/rowstore.py),
    shared by the bench ``rowstore`` phase and the CPU-fallback tier
    (the fleet runs on host numpy + real wire frames by construction
    — no TPU dependency, honest everywhere):

    ``cluster_sparse_pull_fraction`` — MEASURED rank rows the fleet's
    workers actually pulled per iteration over the dense baseline
    (every worker pulling the whole vector): the reason a model
    bigger than one host is trainable at all. Counted from the
    workers' precomputed pull sets, not estimated from degree
    statistics.

    ``pagerank_cluster_iters_per_sec`` — full measured wall clock of
    a cluster PageRank run through the row store: sparse pulls and
    pushes through encoded wire frames, WAL row-redo records per
    commit — the whole protocol, not a kernel microbenchmark.

    Both RAISE instead of emitting fabricated values when the run
    stops early, the rank invariant (Σranks ≈ 1) breaks, or the
    'sparse' pulls turn out dense (fraction ≥ 1 means the claim is
    dead, not small)."""
    import os
    import tempfile

    import numpy as _np

    from tpu_distalg import graphs
    from tpu_distalg.cluster import rowstore

    V = 2048 if fast else 8192
    iters = 4 if fast else 8
    shards = 4
    with tempfile.TemporaryDirectory(prefix="tda_rowstore_") as d:
        path = os.path.join(d, "graph")
        graphs.build_powerlaw_block_cache(
            path, n_vertices=V, n_shards=shards, avg_in_degree=8.0,
            alpha=1.6, seed=3, block_edges=512)
        res = rowstore.run_cluster_pagerank(
            path, rowstore.ClusterPageRankConfig(
                n_iterations=iters,
                wal_dir=os.path.join(d, "wal")))
    if res["version"] != iters:
        raise RuntimeError(
            f"rowstore pagerank stopped at iteration "
            f"{res['version']}/{iters} — refusing to time an "
            f"incomplete run")
    rank_sum = float(_np.sum(res["ranks"], dtype=_np.float64))
    if abs(rank_sum - 1.0) > 1e-2:
        raise RuntimeError(
            f"rank vector sums to {rank_sum:.6f}, not 1 — the "
            f"protocol dropped mass; a rate from a wrong answer is "
            f"not claimable")
    frac = float(res["sparse_pull_fraction"])
    if not 0.0 < frac < 1.0:
        raise RuntimeError(
            f"sparse pull fraction {frac} is not in (0, 1) — the "
            f"pulls were dense (or the accounting broke); refusing "
            f"to claim sparsity")
    shared = {
        "n_vertices": V, "n_workers": res["n_workers"],
        "n_iterations": iters,
        "peak_pull_rows": res["peak_pull_rows"],
        "rank_sum": round(rank_sum, 6),
    }
    emit({
        "metric": "cluster_sparse_pull_fraction",
        "value": round(frac, 4),
        "unit": "fraction",
        "vs_baseline": None,
        **shared,
        "note": "measured rank rows pulled per iteration / dense "
                "baseline (every worker pulls all V rows); from the "
                "workers' actual pull sets on a power-law edge "
                "cache — the >1-host-RAM story in one number",
    })
    emit({
        "metric": "pagerank_cluster_iters_per_sec",
        "value": round(res["iters_per_sec"], 3),
        "unit": "iter/s",
        "vs_baseline": None,
        "elapsed_s": round(res["elapsed_s"], 3),
        **shared,
        "note": "full protocol wall clock: sparse row pulls/pushes "
                "through encoded wire frames + WAL row-redo per "
                "commit; rank invariant and completion asserted, "
                "never assumed",
    })


def run_cluster_serve_bench(emit, *, fast: bool = False):
    """The serving plane's headline triplet (cluster/serve.py +
    cluster/router.py) — host threads by construction, so like the
    training cluster it is honest on every backend:

    ``cluster_serve_qps`` — closed-loop throughput of an undisturbed
    burst through the router against a 3-replica kmeans fleet
    (least-loaded dispatch, micro-batched replicas).

    ``cluster_serve_p99_under_kill_ms`` — CLIENT-observed p99 latency
    (first submit to final answer, retries and backoff included) of
    the same burst while one replica dies to a seeded
    ``cluster:replica`` kill mid-burst and the router re-routes the
    stranded requests. The router-side per-attempt latency would hide
    the re-route cost; the client clock is the one the kill taxes.

    ``cluster_serve_availability`` — fraction of that disturbed
    burst's requests answered on the FIRST client attempt: transparent
    internal re-routes keep it at 1.0; only sheds and dead windows the
    client must retry through lower it.

    All three RAISE instead of emitting fabricated values when the
    burst fails to complete, when the seeded kill never fires (the
    p99/availability pair would describe an undisturbed run), or when
    the disturbed replies diverge bitwise from the undisturbed burst's
    (a fast answer that is wrong is not a served request)."""
    import numpy as _np

    from tpu_distalg.cluster import serve as cserve
    from tpu_distalg.faults import registry as fregistry

    dim, k = 16, 8
    n_req = 96 if fast else 384
    rng = _np.random.default_rng(13)
    center = {"centers":
              rng.standard_normal((k, dim)).astype(_np.float32)}
    payloads = list(rng.standard_normal(
        (n_req, dim)).astype(_np.float32))
    cfg = cserve.FleetConfig(kind="kmeans", n_replicas=3, version=1,
                             max_delay_ms=1.0)

    fleet = cserve.ServeFleet(cfg, center).start()
    try:
        res_a, info_a = cserve.run_fleet_closed_loop(
            fleet, payloads, concurrency=8)
    finally:
        fleet.stop()
    if info_a["failed"] or info_a["ok"] != n_req:
        raise RuntimeError(
            f"undisturbed serve burst incomplete ({info_a['ok']}/"
            f"{n_req} ok, {info_a['failed']} failed) — refusing to "
            f"fabricate a throughput")
    emit({
        "metric": "cluster_serve_qps",
        "value": info_a["qps"],
        "unit": "req/s",
        "vs_baseline": None,
        "n_requests": n_req, "n_replicas": 3,
        "policy": cfg.policy, "concurrency": 8,
        "p99_clean_ms": info_a["p99_ms"],
        "note": "closed-loop burst through the router against a "
                "3-replica kmeans fleet, least-loaded dispatch, "
                "micro-batched replicas — host threads by "
                "construction, honest on every backend",
    })

    # disturbed arm: the SAME burst with one replica killed by a
    # seeded plan mid-burst (hit counts score frames fleet-wide);
    # client retries span the router's heartbeat/revival cadence so a
    # shed window is a latency, never a lost request
    hit = 7 if fast else 13
    plan = f"seed=13;cluster:replica@{hit}=kill"
    fregistry.configure(plan)
    try:
        fleet = cserve.ServeFleet(cfg, center).start()
        try:
            res_b, info_b = cserve.run_fleet_closed_loop(
                fleet, payloads, concurrency=8, retries=10,
                retry_backoff_s=0.05)
            st = fleet.stats()
            killed = [r.slot for r in fleet.replicas if r.killed]
        finally:
            fleet.stop()
    finally:
        fregistry.configure(False)
    if not killed:
        raise RuntimeError(
            "the seeded replica kill never fired — the p99/"
            "availability pair would describe an undisturbed run")
    if info_b["failed"] or info_b["ok"] != n_req:
        raise RuntimeError(
            f"disturbed serve burst incomplete ({info_b['ok']}/"
            f"{n_req} ok, {info_b['failed']} failed) — refusing to "
            f"fabricate a kill-latency")
    for j, (a, b) in enumerate(zip(res_a, res_b)):
        if not _np.array_equal(_np.asarray(a[0]), _np.asarray(b[0])):
            raise RuntimeError(
                f"disturbed reply {j} diverged bitwise from the "
                f"undisturbed burst — re-routing must not tax "
                f"correctness; refusing to emit its latency")
    emit({
        "metric": "cluster_serve_p99_under_kill_ms",
        "value": info_b["p99_ms"],
        "unit": "ms",
        "vs_baseline": None,
        "killed_replicas": killed, "reroutes": st["reroutes"],
        "client_retries": info_b["retries"],
        "p50_under_kill_ms": info_b["p50_ms"],
        "bitwise_vs_undisturbed": True,
        "plan": plan,
        "note": "client-observed p99 (first submit to final answer, "
                "retries included) of the same burst with one "
                "replica killed mid-burst by a seeded plan; every "
                "reply asserted bitwise-identical to the undisturbed "
                "burst's",
    })
    emit({
        "metric": "cluster_serve_availability",
        "value": info_b["availability"],
        "unit": "fraction",
        "vs_baseline": None,
        "killed_replicas": killed, "sheds": st["sheds"],
        "plan": plan,
        "note": "fraction of the disturbed burst answered on the "
                "FIRST client attempt — transparent internal "
                "re-routes keep it at 1.0; only sheds and dead "
                "windows the client retries through lower it",
    })


def _bench_cluster(mesh, n_chips):
    del mesh, n_chips  # the cluster builds its own local worker meshes
    run_cluster_bench(_emit)


def _bench_cluster_serve(mesh, n_chips):
    del mesh, n_chips  # host-thread fleet: no device mesh involved
    run_cluster_serve_bench(_emit)


def _bench_rowstore(mesh, n_chips):
    del mesh, n_chips  # host numpy fleet + wire frames: no device mesh
    run_rowstore_bench(_emit)


def _bench_ssp(mesh, n_chips, sync="bsp"):
    """The SSP straggler phase — see
    :func:`run_ssp_straggler_speedup`. ``--sync ssp:s`` overrides the
    measured staleness bound; off-default bounds record under
    ``_bound{s}``-suffixed metric names so the canonical claim metric
    can never be overwritten (the PR 6 shard-suffix convention)."""
    from tpu_distalg.parallel import ssp as pssp

    spec = pssp.SyncSpec.parse(sync)
    run_ssp_straggler_speedup(
        mesh, _emit,
        staleness=spec.staleness if spec.is_ssp else None)


def _bench_ssgd(mesh, on_tpu, n_chips, comm="dense"):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_distalg.models import ssgd
    from tpu_distalg.ops import logistic
    from tpu_distalg.parallel import parallelize
    from tpu_distalg.utils import datasets, prng

    X, y = datasets.synthetic_two_class(N_ROWS, N_FEATURES, seed=0)
    X = datasets.add_bias_column(X)
    d = X.shape[1]
    n_shards = int(mesh.shape["data"])

    if on_tpu:
        # single-data-shard meshes take the megakernel (whole schedule
        # in one launch per 125-step segment, weights in VMEM); dp>1
        # needs the per-step psum, i.e. 'fused_gather' — which is also
        # the sampler a non-dense --comm schedule needs (the megakernel
        # has no per-step collective to re-schedule)
        sampler = ("fused_train" if n_shards == 1 and comm == "dense"
                   else "fused_gather")
        config = ssgd.SSGDConfig(
            n_iterations=N_STEPS, eval_test=False,
            x_dtype="bfloat16", sampler=sampler,
            gather_block_rows=GATHER_BLOCK_ROWS, shuffle_seed=0,
            init_seed=7, comm=comm,
        )
        fn, X2, w0, meta = ssgd.prepare_fused(X, y, mesh, config)
        dummy = jnp.zeros((1,), jnp.float32)
        ev = (jnp.zeros((1, meta["d_total"]), jnp.float32),
              jnp.zeros((1,), jnp.float32))
        args = (X2, dummy, dummy, ev[0], ev[1])
        _, n_sampled_local = ssgd.fused_gather_geometry(
            config, meta, n_shards)
        bytes_per_step = (n_sampled_local * n_shards * GATHER_BLOCK_ROWS
                          * int(meta["d_total"]) * 2)  # bf16
    else:
        config = ssgd.SSGDConfig(n_iterations=N_STEPS, eval_test=False,
                                 comm=comm)
        Xs, ys = parallelize(X, mesh), parallelize(y, mesh)
        w0 = logistic.init_weights(prng.root_key(7), d)
        fn = ssgd.make_train_fn(mesh, config, Xs.n_padded, d=d)
        ev = (jnp.zeros((1, d), jnp.float32), jnp.zeros((1,), jnp.float32))
        args = (Xs.data, ys.data, Xs.mask, ev[0], ev[1])
        bytes_per_step = Xs.n_padded * d * 4 * 2  # f32, fwd+bwd passes

    from tpu_distalg.utils import profiling

    if comm != "dense":
        # comm-schedule fns thread the error-feedback residual
        from jax.sharding import NamedSharding, PartitionSpec as P

        sync = ssgd._comm_sync(
            mesh, config, int(meta["d_total"]) if on_tpu else d)
        res0 = jax.device_put(
            jnp.asarray(sync.init_state()),
            NamedSharding(mesh, P("data", None)))
        timed_fn = lambda: fn(*args, w0, res0)  # noqa: E731
    else:
        timed_fn = lambda: fn(*args, w0)  # noqa: E731

    # device timing via single-element host fetch (steps_per_sec) — on
    # tunneled TPU backends block_until_ready can return early
    best, spread = profiling.steps_per_sec(
        timed_fn, steps=N_STEPS, repeats=N_REPEATS,
        with_stats=True, chain=N_CHAIN)
    per_chip = best / n_chips

    # measured baseline stand-in: identical update, driver-loop shape —
    # one jit dispatch + host round-trip per step (the reference's
    # job-per-step pattern, ssgd.py:93-103, minus all Spark overheads)
    one_cfg = ssgd.SSGDConfig(n_iterations=1, eval_test=False)
    if on_tpu:
        one_cfg = ssgd.SSGDConfig(
            n_iterations=1, eval_test=False, x_dtype="bfloat16",
            sampler="fused_gather", gather_block_rows=GATHER_BLOCK_ROWS,
            shuffle_seed=0, init_seed=7)
        one_fn = ssgd.make_train_fn_fused(mesh, one_cfg, meta)
    else:
        one_fn = ssgd.make_train_fn(mesh, one_cfg, Xs.n_padded)
    state = {"w": w0, "t": 0}

    def one_iter():
        state["w"] = jnp.asarray(
            np.asarray(one_fn(*args, state["w"], state["t"])[0]))
        state["t"] += 1

    measured_baseline = _measured_driver_baseline(one_iter, n_base=20)
    denom, floor = _floor_denominator(measured_baseline, best)

    # convergence evidence on the reference task (TPU kernels only)
    conv = {}
    if on_tpu:
        import warnings

        data = datasets.breast_cancer_split()
        with warnings.catch_warnings():
            warnings.filterwarnings("ignore", message="fused_gather:")
            conv["convergence_acc_fused"] = round(ssgd.train(
                *data, mesh,
                ssgd.SSGDConfig(n_iterations=1500, sampler="fused"),
            ).final_acc, 6)
            conv["convergence_acc_fused_gather"] = round(ssgd.train(
                *data, mesh,
                ssgd.SSGDConfig(n_iterations=1500,
                                sampler="fused_gather",
                                fused_pack=4, gather_block_rows=32,
                                shuffle_seed=0),
            ).final_acc, 6)
            if n_shards == 1:
                # eval at the last megakernel segment boundary == the
                # trained weights' test accuracy
                conv["convergence_acc_fused_train"] = round(ssgd.train(
                    *data, mesh,
                    ssgd.SSGDConfig(n_iterations=1500,
                                    sampler="fused_train",
                                    mega_steps=125, eval_every=125,
                                    fused_pack=4, gather_block_rows=32,
                                    shuffle_seed=0),
                ).final_acc, 6)

    _emit({
        "metric": "ssgd_lr_steps_per_sec_per_chip",
        "value": round(per_chip, 2),
        "unit": "steps/s/chip",
        "vs_baseline": round(per_chip / denom, 2),
        "sampler": config.sampler,
        "comm": config.comm,
        "x_dtype": config.x_dtype,
        "n_rows": N_ROWS,
        "n_features": N_FEATURES,
        "steps_per_segment": N_STEPS,
        "bytes_per_step": bytes_per_step,
        "hbm_peak_fraction": _hbm_fraction(bytes_per_step, best,
                                           n_shards),
        "baseline_steps_per_sec_measured": round(measured_baseline, 2),
        "baseline_floor_steps_per_sec": round(floor, 2),
        "baseline_method": (
            "jit-per-step host-roundtrip loop (measured); vs_baseline "
            "divides by max(measured, floor) where floor = an idealized "
            f"Spark driver at {ASSUMED_SPARK_JOBS_PER_SEC} jobs/s paying "
            "the same per-step device compute"),
        "spread": spread,
        **conv,
    })

    if on_tpu and config.sampler == "fused_train":
        # the flagship megakernel is the dp=1 specialization; record the
        # dp>1-valid sampler ('fused_gather', per-step psum) at the SAME
        # geometry next to it, so the artifact carries the multi-chip-
        # relevant rate too (r3 verdict ask #6)
        g_cfg = ssgd.SSGDConfig(
            n_iterations=N_STEPS, eval_test=False, x_dtype="bfloat16",
            sampler="fused_gather", gather_block_rows=GATHER_BLOCK_ROWS,
            shuffle_seed=0, init_seed=7)
        g_fn = ssgd.make_train_fn_fused(mesh, g_cfg, meta)
        g_best, g_spread = profiling.steps_per_sec(
            lambda: g_fn(*args, w0, 0), steps=N_STEPS,
            repeats=N_REPEATS, with_stats=True, chain=N_CHAIN)
        _emit({
            "metric": "ssgd_lr_fused_gather_steps_per_sec_per_chip",
            "value": round(g_best / n_chips, 2),
            "unit": "steps/s/chip",
            "vs_baseline": None,
            "vs_flagship_megakernel": round(g_best / best, 3),
            "note": "the dp>1-valid sampler (per-step psum) at the "
                    "flagship's exact geometry — the rate a multi-chip "
                    "data mesh runs at",
            "sampler": "fused_gather",
            "x_dtype": "bfloat16",
            "n_rows": N_ROWS,
            "spread": g_spread,
        })
    return per_chip


def _bench_ssgd_scale(mesh, n_chips):
    """100M-row scale proof (TPU only): the packed design matrix is
    synthesized ON DEVICE (``ssgd.prepare_fused_synthetic``) — host
    memory stays O(1) in the row count, the property the 1B-row
    north star needs (at 1B rows the per-shard synthesis is identical,
    just spread over a v5e-16's 16 HBMs)."""
    import jax.numpy as jnp
    import numpy as np

    from tpu_distalg.models import ssgd

    def peak_rss_gb():
        # VmHWM = high-water mark: monotonic, so the delta across the
        # generation captures transient host allocations too
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmHWM:"):
                    return int(line.split()[1]) / 1e6
        return -1.0

    n_rows, n_steps, n_features = 100_000_000, 500, 30
    rss_before = peak_rss_gb()
    # blocks 16x the 1M-row bench's: at this scale the grid is the
    # overhead (1221 sampled blocks/step at 8192 rows → 0.63 of
    # roofline; 76 at 131072 → 0.87 measured). Coarser block-cluster
    # draws are statistically free here — rows come from a
    # counter-based per-row PRNG, i.i.d. by construction
    cfg = ssgd.SSGDConfig(
        n_iterations=n_steps, eval_test=False, x_dtype="bfloat16",
        sampler="fused_gather", gather_block_rows=131072,
        init_seed=7)
    t0 = time.perf_counter()
    fn, X2, w0, meta = ssgd.prepare_fused_synthetic(
        n_rows, n_features, mesh, cfg)
    np.asarray(X2[:1])  # force generation
    gen_seconds = time.perf_counter() - t0
    rss_delta = max(0.0, peak_rss_gb() - rss_before)
    dummy = jnp.zeros((1,), jnp.float32)
    ev = (jnp.zeros((1, meta["d_total"]), jnp.float32),
          jnp.zeros((1,), jnp.float32))

    from tpu_distalg.utils import profiling

    best, spread, (w, _) = profiling.steps_per_sec(
        lambda: fn(X2, dummy, dummy, ev[0], ev[1], w0),
        steps=n_steps, repeats=N_REPEATS, with_stats=True,
        with_output=True, chain=4)  # ~0.9 s/call: 4 calls amortize the
    #                                 ~100 ms round-trip to <3%

    # held-out accuracy of the trained weights: fresh rows from the same
    # counter-based generator (ids beyond the training range) — proves
    # the 100M-row run learns, not just streams
    import jax

    from tpu_distalg.utils import datasets as dsets
    from tpu_distalg.utils import metrics as mtr

    n_heldout = 4096
    d = n_features + 1  # + bias, matching prepare_fused_synthetic
    make_rows = dsets.synthetic_two_class_rows(n_features, seed=0)
    X_ho, y_ho = jax.jit(make_rows)(
        jnp.arange(n_rows, n_rows + n_heldout, dtype=jnp.int32))
    X_ho = jnp.concatenate([X_ho, jnp.ones((n_heldout, 1))], axis=1)
    acc = float(mtr.binary_accuracy(X_ho @ jnp.asarray(w)[:d], y_ho))

    n_shards = int(mesh.shape["data"])
    _, n_sampled = ssgd.fused_gather_geometry(cfg, meta, n_shards)
    bytes_per_step = (n_sampled * n_shards * cfg.gather_block_rows
                      * int(meta["d_total"]) * 2)
    _emit({
        "metric": "ssgd_lr_100m_rows_steps_per_sec_per_chip",
        "value": round(best / n_chips, 2),
        "unit": "steps/s/chip",
        "vs_baseline": None,
        "n_rows": n_rows,
        "n_features": n_features,
        "data_path": "on-device per-shard synthesis (host RAM O(1))",
        "hbm_peak_fraction": _hbm_fraction(bytes_per_step, best,
                                           n_shards),
        "hbm_bytes_dataset": int(X2.size) * 2,
        "generation_seconds": round(gen_seconds, 1),
        # host memory the 8 GB dataset cost: ~0 (synthesized on device);
        # delta of the peak-RSS high-water mark across generation
        "host_rss_delta_gb": round(rss_delta, 2),
        "heldout_acc": round(acc, 4),
        "spread": spread,
    })


def _bench_local_sgd(mesh, n_chips, ssgd_per_chip):
    """The local-update family at benchmark scale (TPU only): MA's local
    step runs the SAME packed traffic-proportional kernel as the SSGD
    flagship (``local_sgd.make_train_fn_fused``), so the family's step
    rate is recorded next to SSGD's instead of silently streaming f32
    through the XLA path (the r2 verdict's pathology). One metric step =
    one LOCAL step; the round-end pmean amortizes over
    ``n_local_iterations``. Reference: ``optimization/ma.py:98-106``."""
    import jax.numpy as jnp

    from tpu_distalg.models import ma
    from tpu_distalg.utils import datasets, profiling

    X, y = datasets.synthetic_two_class(N_ROWS, N_FEATURES, seed=0)
    X = datasets.add_bias_column(X)
    n_rounds, n_local = 300, 5
    cfg = ma.MAConfig(
        n_iterations=n_rounds, n_local_iterations=n_local,
        eval_test=False, sampler="fused_train", x_dtype="bfloat16",
        gather_block_rows=GATHER_BLOCK_ROWS, shuffle_seed=0,
    )
    from tpu_distalg.models import local_sgd

    fn, X2, w0, ws0, delta0, meta = local_sgd.prepare_fused(
        X, y, mesh, cfg)
    ev = (jnp.zeros((1, meta["d_total"]), jnp.float32),
          jnp.zeros((1,), jnp.float32))
    best, spread = profiling.steps_per_sec(
        lambda: fn(X2, ev[0], ev[1], w0, ws0, delta0),
        steps=n_rounds * n_local, repeats=N_REPEATS, with_stats=True,
        chain=N_CHAIN)
    per_chip = best / n_chips

    # convergence evidence on the reference task
    import warnings

    data = datasets.breast_cancer_split()
    with warnings.catch_warnings():
        warnings.filterwarnings("ignore", message="fused_gather:")
        conv = ma.train(*data, mesh, ma.MAConfig(
            n_iterations=300, sampler="fused_train",
            gather_block_rows=64, fused_pack=4, shuffle_seed=0,
        )).final_acc

    _emit({
        "metric": "ma_local_sgd_local_steps_per_sec_per_chip",
        "value": round(per_chip, 2),
        "unit": "local steps/s/chip",
        "vs_baseline": None,
        "vs_ssgd_flagship": (
            round(per_chip / ssgd_per_chip, 3) if ssgd_per_chip else None),
        "sampler": cfg.sampler,
        "x_dtype": cfg.x_dtype,
        "n_rows": N_ROWS,
        "n_rounds": n_rounds,
        "n_local_iterations": n_local,
        "convergence_acc_fused_train": round(conv, 6),
        "spread": spread,
    })


def _bench_kmeans_scale(mesh, n_chips):
    """k-means at 10M points (TPU only), fully on the scale path: the
    mixture is synthesized ON DEVICE (``kmeans.fit_scaled`` /
    ``build_sharded``) and the init centers are regenerated from k row
    ids — host memory O(k), where the reference materializes the whole
    dataset on the driver (``machine_learning/k-means.py:49-53``)."""
    import numpy as np

    from tpu_distalg.models import kmeans
    from tpu_distalg.utils import datasets, profiling

    # 50 iters/call: at ~2.8 ms/iter a 20-iter call is ~56 ms of device
    # time vs the ~100 ms tunnel round-trip — longer calls keep the
    # chain-amortized residue under ~5%
    n_rows, k, dim, iters = 10_000_000, 8, 16, 50
    make_rows, true_centers = datasets.gaussian_mixture_rows(
        k=k, dim=dim, seed=0, spread=8.0)
    cfg = kmeans.KMeansConfig(k=k, n_iterations=iters, seed=0,
                              init="farthest")

    from tpu_distalg.parallel import build_sharded

    ps = build_sharded(mesh, n_rows, make_rows)
    centers0 = kmeans.init_centers_scaled(make_rows, n_rows, cfg)
    fn = kmeans.make_fit_fn(mesh, cfg)
    best, spread, (centers, _, _) = profiling.steps_per_sec(
        lambda: fn(ps.data, ps.mask, centers0),
        steps=iters, repeats=N_REPEATS, with_stats=True,
        with_output=True, chain=N_CHAIN)  # ~70 ms/call since the
    #                               one-hot-matmul cluster_stats

    # recovery evidence: every true mixture mean found
    got = np.asarray(centers)
    want = np.asarray(true_centers())
    d = np.linalg.norm(got[:, None, :] - want[None, :, :], axis=-1)
    recovered = (sorted(d.argmin(axis=1).tolist()) == list(range(k))
                 and float(d.min(axis=1).max()) < 0.1)

    # measured baseline stand-in, as for SSGD/PageRank: the reference's
    # driver shape is one job per iteration (k-means.py:59-75 collects
    # per iteration); here that is a 1-iteration jit call + host
    # round-trip per iteration
    import jax.numpy as jnp

    one_fn = kmeans.make_fit_fn(
        mesh, kmeans.KMeansConfig(k=k, n_iterations=1, seed=0,
                                  init="farthest"))
    state = {"c": centers0}

    def one_iter():
        state["c"] = jnp.asarray(
            np.asarray(one_fn(ps.data, ps.mask, state["c"])[0]))

    measured_baseline = _measured_driver_baseline(one_iter)
    denom, floor = _floor_denominator(measured_baseline, best)

    _emit({
        "metric": "kmeans_10m_iters_per_sec_per_chip",
        "value": round(best / n_chips, 3),
        "unit": "iter/s/chip",
        "vs_baseline": round(best / n_chips / denom, 2),
        "baseline_iters_per_sec_measured": round(measured_baseline, 3),
        "baseline_floor_iters_per_sec": round(floor, 3),
        "baseline_method": "jit-per-iteration host-roundtrip loop "
                           "(measured, the reference's job-per-"
                           "iteration driver shape); vs_baseline "
                           "divides by max(measured, floor) where "
                           "floor = an idealized Spark driver at "
                           f"{ASSUMED_SPARK_JOBS_PER_SEC} jobs/s paying "
                           "the same per-iteration device compute",
        "n_points": n_rows,
        "k": k,
        "dim": dim,
        "data_path": "on-device per-shard synthesis + O(k)-host init",
        "centers_recovered": bool(recovered),
        "spread": spread,
    })


def _bench_ssgd_virtual(mesh, n_chips):
    """The >HBM story (TPU only): SSGD over a 1B-row LOGICAL dataset on
    whatever chips are present — ~7.8x one v5e's HBM if materialised
    f32 at d=31 (the emitted ``hbm_ratio_f32`` field computes it). No row is ever
    stored: each step regenerates exactly the sampled blocks from the
    counter-based row generator (models/ssgd_virtual.py), replacing the
    Spark spill/lineage capability the reference gets silently from
    .cache() (optimization/ssgd.py:86). Convergence is checked the same
    way as the 100M resident-HBM line: held-out accuracy from the same
    generator (r03 recorded 0.7898 there; same band expected here)."""
    import jax.numpy as jnp

    from tpu_distalg.models import ssgd, ssgd_virtual
    from tpu_distalg.ops import logistic
    from tpu_distalg.utils import metrics as mtr
    from tpu_distalg.utils import profiling, prng

    n_rows, n_steps, n_features = 1_000_000_000, 200, 30
    data = ssgd_virtual.VirtualData(n_rows=n_rows, n_features=n_features,
                                    data_seed=0)
    cfg = ssgd.SSGDConfig(
        n_iterations=n_steps, eval_test=False, sampler="virtual",
        mini_batch_fraction=0.01, gather_block_rows=131072, init_seed=7)
    fn = ssgd_virtual.make_train_fn(mesh, cfg, data)
    w0 = logistic.init_weights(prng.root_key(cfg.init_seed), data.d)
    dummy = jnp.zeros((1,), jnp.float32)
    ev = (jnp.zeros((1, data.d), jnp.float32),
          jnp.zeros((1,), jnp.float32))
    best, spread, (w, _) = profiling.steps_per_sec(
        lambda: fn(dummy, dummy, dummy, ev[0], ev[1], w0),
        steps=n_steps, repeats=N_REPEATS, with_stats=True,
        with_output=True, chain=2)
    X_ho, y_ho = ssgd_virtual.heldout_set(data, 8192)
    acc = float(mtr.binary_accuracy(X_ho @ jnp.asarray(w), y_ho))
    n_shards = int(mesh.shape["data"])
    _, n_blocks, n_sampled = ssgd_virtual._geometry(cfg, data, n_shards)
    rows_per_step = n_sampled * n_shards * cfg.gather_block_rows
    _emit({
        "metric": "ssgd_lr_1b_rows_virtual_steps_per_sec_per_chip",
        "value": round(best / n_chips, 2),
        "unit": "steps/s/chip",
        "vs_baseline": None,
        "n_rows_logical": n_rows,
        "n_features": n_features,
        "logical_dataset_bytes_f32": n_rows * data.d * 4,
        "hbm_ratio_f32": round(n_rows * data.d * 4 / 16e9, 1),
        "rows_regenerated_per_step": rows_per_step,
        "rows_regenerated_per_sec": round(best * rows_per_step / 1e9, 2),
        "rows_regenerated_per_sec_unit": "Grows/s",
        "data_path": "no resident dataset — sampled blocks regenerated "
                     "on device per step (counter-based PRNG)",
        "heldout_acc": round(acc, 4),
        "heldout_acc_resident_100m_r03": 0.7898,
        "spread": spread,
    })


def _bench_ssgd_stream(mesh, n_chips):
    """The REAL->HBM story (TPU only): SSGD over a 32.8 GB disk-backed
    dataset — 2.05x one v5e's HBM of OPAQUE bytes (a noisy
    linear-teacher task generated once into a memmap cache, then
    treated as data: unlike the 'virtual' sampler, row content is NOT
    a function of the row id, so the trainer must MOVE the bytes).
    Per step the sampled blocks are host-gathered and staged with an
    async device_put, double-buffered behind the device step
    (models/ssgd_stream.py) — replacing Spark's partition spill/stream
    (reference optimization/ssgd.py:86). The rig's H2D roofline is
    measured in-process with a FORCED full-array consumption after the
    put — on this tunneled rig a bare device_put+block_until_ready is
    LAZY and reports ~0.5-1.3 GB/s while the transfer that actually
    feeds a computation runs at ~15-30 MB/s. steps/s here therefore
    measures the RIG's true H2D path at full utilization, not the TPU;
    the per-step bytes are sized so the line stays honest AND finishes
    (4×2048-row sampled blocks = 2 MB/step)."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from tpu_distalg.models import ssgd, ssgd_stream
    from tpu_distalg.ops import logistic
    from tpu_distalg.utils import datasets, metrics as mtr, prng

    n_shards = int(mesh.shape["data"])
    n_rows = 128 * (1 << 20)            # x128-wide bf16 rows = 32.8 GB
    cache = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         ".bench_cache", "stream128m")
    os.makedirs(os.path.dirname(cache), exist_ok=True)
    t_gen = time.perf_counter()
    X2, meta, (X_test, y_test) = datasets.streamed_packed_cache(
        cache, n_rows=n_rows, n_features=N_FEATURES,
        n_shards=n_shards, pack=16, gather_block_rows=2048, seed=0)
    gen_s = time.perf_counter() - t_gen
    d = N_FEATURES + 1
    # 4 sampled 2048-row blocks per step = 2 MB H2D — an 8192-row
    # minibatch, sized for the tunnel's ~15-30 MB/s true H2D rate
    cfg = ssgd.SSGDConfig(
        n_iterations=30, eval_test=False, sampler="fused_gather",
        x_dtype="bfloat16", mini_batch_fraction=4 / 65536,
        gather_block_rows=2048, init_seed=7, shuffle_seed=None)
    trainer = ssgd_stream.StreamTrainer(X2, meta, mesh, cfg)
    w0 = jnp.zeros((meta["d_total"],), jnp.float32).at[:d].set(
        logistic.init_weights(prng.root_key(cfg.init_seed), d))

    w = trainer.run(w0, 0, 3)[0]        # compile + page-cache warm
    jax.block_until_ready(w)
    # rig H2D roofline: one staged batch with a FORCED full-array
    # consumption (fetching the reduction) — a bare put is lazy here
    ids = ssgd_stream.host_block_ids(
        cfg, n_shards, trainer.n_blocks, trainer.n_sampled,
        np.arange(3))
    raw_bw = 0.0
    for i in range(3):
        t0 = time.perf_counter()
        np.asarray(trainer._touch(trainer._stage(ids[i]))).sum()
        raw_bw = max(raw_bw, trainer.h2d_bytes_per_step
                     / (time.perf_counter() - t0))

    steps, t_abs, rates = 30, 3, []
    for _ in range(N_REPEATS):
        t0 = time.perf_counter()
        w = trainer.run(w, t_abs, steps)[0]
        jax.block_until_ready(w)
        rates.append(steps / (time.perf_counter() - t0))
        t_abs += steps
    best = max(rates)

    t = np.load(cache + ".test.npz")
    Xt = np.pad(np.asarray(X_test, np.float32),
                ((0, 0), (0, meta["d_total"] - d)))
    acc = float(mtr.binary_accuracy(
        jnp.asarray(Xt) @ w, jnp.asarray(y_test)))
    teacher_acc = float(np.mean(
        (X_test @ t["w_true"] > 0) == (y_test > 0.5)))
    dataset_bytes = int(X2.shape[0]) * int(X2.shape[1]) * 2
    achieved = trainer.h2d_bytes_per_step * best
    _emit({
        "metric": "ssgd_lr_32gb_streamed_steps_per_sec_per_chip",
        "value": round(best / n_chips, 2),
        "unit": "steps/s/chip",
        "vs_baseline": None,
        "n_rows": n_rows,
        "dataset_bytes": dataset_bytes,
        "hbm_ratio": round(dataset_bytes / 16e9, 2),
        "data_path": "disk-memmap host dataset; sampled blocks "
                     "host-gathered on a one-deep prefetch thread + "
                     "async device_put, double-buffered "
                     "(models/ssgd_stream.py)",
        "minibatch_rows_per_step": trainer.h2d_bytes_per_step
        // (meta["d_total"] * 2),
        "h2d_bytes_per_step": trainer.h2d_bytes_per_step,
        "achieved_h2d_gb_per_sec": round(achieved / 1e9, 3),
        "serial_device_put_gb_per_sec": round(raw_bw / 1e9, 3),
        # >1 means the double-buffering hides put latency behind the
        # step: the pipelined loop beats a serial put+consume
        "h2d_overlap_vs_serial": round(achieved / raw_bw, 2),
        "heldout_acc": round(acc, 4),
        "teacher_ceiling_acc": round(teacher_acc, 4),
        "cache_generation_seconds": round(gen_s, 1),
        "spread": {"repeats": N_REPEATS,
                   "best": round(max(rates), 2),
                   "median": round(sorted(rates)[len(rates) // 2], 2),
                   "min": round(min(rates), 2)},
    })


def _bench_kmeans_streamed(mesh, n_chips):
    """k-means over >HBM REAL bytes (TPU only) — the capability the
    data subsystem opened (the r6 verdict's "what's missing" #3:
    k-means silently capped at one chip's HBM): a 268M-point
    Gaussian-mixture cache on disk (18.3 GB of f32 points + validity,
    1.14x one v5e's HBM), minibatch k-means streaming sampled blocks
    per step through the prefetch pipeline (gather ∥ H2D ∥ compute).
    Recovery evidence: every true mixture mean found from the streamed
    minibatches alone."""
    import numpy as np

    from tpu_distalg.data import builders
    from tpu_distalg.models import kmeans

    n_rows = 256 * (1 << 20)     # x (16+1) f32 columns = 18.3 GB
    k, dim, steps, mb_blocks = 8, 16, 30, 4
    cache = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         ".bench_cache", "kmeans_pts268m")
    t_gen = time.perf_counter()
    ds, true_centers = builders.gaussian_points_dataset(
        mesh, n_rows, dim=dim, k=k, seed=0, block_rows=2048,
        backend="streamed", path=cache)
    gen_s = time.perf_counter() - t_gen
    cfg = kmeans.KMeansConfig(k=k, seed=0)
    c0 = kmeans.init_centers_from_dataset(ds, k, cfg.seed)

    import jax

    t0 = time.perf_counter()
    res = kmeans.fit_minibatch(ds, cfg, n_steps=steps,
                               mini_batch_blocks=mb_blocks,
                               centers0=c0)
    jax.block_until_ready(res.centers)
    dt = time.perf_counter() - t0
    best = steps / dt

    got = np.asarray(res.centers)
    want = np.asarray(true_centers)
    d2 = np.linalg.norm(got[:, None, :] - want[None, :, :], axis=-1)
    recovered = (sorted(d2.argmin(axis=1).tolist()) == list(range(k))
                 and float(d2.min(axis=1).max()) < 0.5)
    step_bytes = ds.h2d_bytes_per_step(mb_blocks)
    dataset_bytes = ds.n2 * ds.pd * ds.itemsize
    _emit({
        "metric": "kmeans_18gb_streamed_steps_per_sec_per_chip",
        "value": round(best / n_chips, 2),
        "unit": "steps/s/chip",
        "vs_baseline": None,
        "n_points": n_rows,
        "k": k, "dim": dim,
        "dataset_bytes": dataset_bytes,
        "hbm_ratio": round(dataset_bytes / 16e9, 2),
        "data_path": "disk packed cache (points_valid_f32); sampled "
                     "blocks streamed via tpu_distalg/data pipeline "
                     "(--data-backend streamed)",
        "minibatch_rows_per_step": mb_blocks * 2048
        * int(mesh.shape["data"]),
        "h2d_bytes_per_step": step_bytes,
        "achieved_h2d_gb_per_sec": round(step_bytes * best / 1e9, 3),
        "centers_recovered": bool(recovered),
        "cache_generation_seconds": round(gen_s, 1),
    })


def _bench_als_streamed(mesh, n_chips):
    """ALS over a >HBM dense R (TPU only): 65536x65536 f32 = 17.2 GB
    (1.07x one v5e's HBM) rank-64 target on disk, solved by streaming
    R row-blocks per solve epoch (models/als.fit_streamed) — R is
    bounded by DISK, not HBM, the scale the reference's
    broadcast-everything ALS cannot touch (SURVEY §2.3). One sweep +
    one streamed RMSE evaluation pass; on a tunneled rig the epoch is
    H2D-bound, so the line records the achieved H2D rate next to the
    sweep rate."""
    import jax

    from tpu_distalg.data import builders
    from tpu_distalg.models import als

    m = n = 65536
    k, block_rows = 64, 512
    cache = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         ".bench_cache", "als_r64k")
    t_gen = time.perf_counter()
    ds, _ = builders.rank_k_rows_dataset(
        mesh, m, n, k, seed=0, block_rows=block_rows,
        backend="streamed", path=cache)
    gen_s = time.perf_counter() - t_gen
    cfg = als.ALSConfig(m=m, n=n, k=k, lam=0.0, n_iterations=1)
    t0 = time.perf_counter()
    res = als.fit_streamed(ds, cfg, rmse_every=0)
    jax.block_until_ready(res.V)
    dt = time.perf_counter() - t0
    dataset_bytes = ds.n2 * ds.pd * ds.itemsize
    # one solve epoch + one RMSE pass each read all of R once
    passes = 2
    _emit({
        "metric": "als_17gb_streamed_sweeps_per_sec_per_chip",
        "value": round(cfg.n_iterations / dt / n_chips, 5),
        "unit": "sweeps/s/chip",
        "vs_baseline": None,
        "m": m, "n": n, "k": k,
        "dataset_bytes": dataset_bytes,
        "hbm_ratio": round(dataset_bytes / 16e9, 2),
        "data_path": "disk packed cache (dense_rows_f32); R row-blocks "
                     "streamed per solve epoch via tpu_distalg/data "
                     "pipeline (--data-backend streamed)",
        "rows_solved_per_sec": round(m * cfg.n_iterations / dt, 1),
        "achieved_h2d_gb_per_sec": round(
            passes * dataset_bytes * cfg.n_iterations / dt / 1e9, 3),
        "rmse_after_1_sweep": round(float(res.rmse_history[-1]), 6),
        "cache_generation_seconds": round(gen_s, 1),
    })


def _bench_pagerank(mesh, n_chips):
    import numpy as np

    from tpu_distalg.models import pagerank
    from tpu_distalg.ops import graph as gops
    from tpu_distalg.utils import datasets

    edges = datasets.erdos_renyi_edges(PR_VERTICES, PR_AVG_DEGREE, seed=0)
    el = gops.prepare_edges(edges, PR_VERTICES)
    de = pagerank.prepare_device_edges(el, mesh)
    de.spmv = pagerank.prepare_device_spmv(el, mesh)

    from tpu_distalg.utils import profiling

    # A/B all three sweep paths: the fully-fused tiled SpMV (Path E,
    # r5 — gather AND scatter in one kernel), the hybrid XLA-gather +
    # Pallas-scatter, and the XLA-only sweep — recorded the way
    # ops/pallas_kmeans.py's negative result was
    rates = {}
    for scatter in ("spmv", "pallas", "xla"):
        if scatter == "pallas" and de.plan is None:
            continue
        if scatter == "spmv" and de.spmv is None:
            continue
        cfg = pagerank.PageRankConfig(
            n_iterations=PR_ITERS_PER_CALL, mode="standard",
            scatter=scatter)
        fn = pagerank.make_run_fn(
            mesh, cfg, de.n_vertices,
            de.plan if scatter == "pallas" else None,
            de.spmv if scatter == "spmv" else None)
        rates[scatter] = profiling.steps_per_sec(
            lambda: fn(de.src, de.dst, de.w_e, de.emask, de.has_out,
                       de.n_ref),
            steps=PR_ITERS_PER_CALL, repeats=N_REPEATS, with_stats=True)
    primary = max(rates, key=lambda k: rates[k][0])
    best, spread = rates[primary]
    per_chip = best / n_chips

    # measured baseline stand-in, as for SSGD: the reference's driver
    # shape — one job per iteration (graph_computation/pagerank.py:50-57
    # rebuilds the lineage each loop; execution happens per collect) —
    # is a 1-iteration jit call + host round-trip per iteration here
    one_fn = pagerank.make_run_fn(
        mesh, pagerank.PageRankConfig(n_iterations=1, mode="standard"),
        de.n_vertices)

    def one_iter():
        np.asarray(one_fn(de.src, de.dst, de.w_e, de.emask,
                          de.has_out, de.n_ref)[0][:1])

    measured_baseline = _measured_driver_baseline(one_iter)
    denom, floor = _floor_denominator(measured_baseline, best)

    # achieved PER-CHIP time per edge. The XLA sweep is bounded by its
    # two random-access ops (~8 ns/elem each: ranks[src] gather + the
    # segment_sum — models/pagerank.py docstring); the Pallas scatter
    # removes one of them, leaving the gather as the floor. Edges are
    # sharded over the data axis, so each chip sweeps n_edges/n_shards
    # per iteration — ×n_shards keeps the number comparable on
    # multi-chip meshes.
    n_shards = int(mesh.shape["data"])
    ns_per_edge = 1e9 * n_shards / (best * float(el.n_edges))

    out = {
        "metric": "pagerank_1m_iters_per_sec",
        "value": round(per_chip, 3),
        "unit": "iter/s/chip",
        "vs_baseline": round(per_chip / denom, 2),
        "baseline_iters_per_sec_measured": round(measured_baseline, 3),
        "baseline_floor_iters_per_sec": round(floor, 3),
        "baseline_method": "jit-per-iteration host-roundtrip loop "
                           "(measured, the reference's job-per-iteration "
                           "driver shape); vs_baseline divides by "
                           "max(measured, floor) where floor = an "
                           "idealized Spark driver at "
                           f"{ASSUMED_SPARK_JOBS_PER_SEC} jobs/s paying "
                           "the same per-iteration device compute",
        "scatter_path": primary,
        "ns_per_edge": round(ns_per_edge, 2),
        "n_vertices": PR_VERTICES,
        "n_edges": int(el.n_edges),
        "mode": "standard",
        "iters_per_call": PR_ITERS_PER_CALL,
        "spread": spread,
    }
    for name, (r_best, r_spread) in rates.items():
        if name == primary:
            continue
        out[f"{name}_iters_per_sec_per_chip"] = round(
            r_best / n_chips, 3)
        out[f"{name}_ns_per_edge"] = round(
            1e9 * n_shards / (r_best * float(el.n_edges)), 2)
        out[f"{name}_spread"] = r_spread
        out[f"{primary}_vs_{name}"] = round(best / r_best, 2)
    _emit(out)


def _bench_pagerank_streamed(mesh, n_chips):
    """Out-of-core PageRank at 100M vertices (ROADMAP item 3): a
    power-law edge-block cache bigger than one chip's HBM, swept by the
    streamed engine (disk gather ∥ H2D ∥ SpMV) with the sparse rank
    combine — the edge bytes NEVER become device-resident, only the
    O(V) rank/degree vectors do. The wire accounting in the line is
    the sparse-vs-dense combine proof at this geometry."""
    import jax

    from tpu_distalg import graphs
    from tpu_distalg.parallel import comms

    n_shards = int(mesh.shape["data"])
    # shard count is baked into the cache geometry at ingest — key the
    # path on it so a different-size rig regenerates instead of failing
    # the geometry check against the previous rig's 19 GB cache forever
    cache = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         ".bench_cache", f"pagerank_pl100m_s{n_shards}")
    t_gen = time.perf_counter()
    _, header = graphs.build_powerlaw_block_cache(
        cache, n_vertices=PR100M_VERTICES, n_shards=n_shards,
        avg_in_degree=PR100M_AVG_IN_DEGREE, alpha=PR100M_ALPHA,
        seed=0)
    gen_s = time.perf_counter() - t_gen
    geom = header["geom"]
    edge_bytes = int(geom["n_edges"]) * 12  # 3 × int32 per edge row
    gd = graphs.open_graph_dataset(cache, mesh, backend="streamed")
    cfg = graphs.StreamedPageRankConfig(n_iterations=PR100M_ITERS)

    # one warmup sweep (compiles + faults in the page cache's cold
    # tail), then the timed full-cache sweeps
    warm = graphs.run_streamed_pagerank(
        gd, graphs.StreamedPageRankConfig(n_iterations=1))
    jax.block_until_ready(warm.ranks)
    t0 = time.perf_counter()
    res = graphs.run_streamed_pagerank(gd, cfg)
    jax.block_until_ready(res.ranks)
    dt = time.perf_counter() - t0
    per_chip = PR100M_ITERS / dt / n_chips
    st = comms.rank_combine_stats(gd.k_sparse, gd.n_vertices,
                                  gd.n_shards)
    _emit({
        "metric": "pagerank_100m_iters_per_sec",
        "value": round(per_chip, 4),
        "unit": "iter/s/chip",
        "vs_baseline": None,
        "n_vertices": int(geom["n_vertices"]),
        "n_edges": int(geom["n_edges"]),
        "edge_bytes_on_disk": edge_bytes,
        "exceeds_one_chip_hbm": edge_bytes > 16 * (1 << 30),
        "combine": res.combine,
        "combine_bytes_wire_per_sweep": st["bytes_wire"],
        "combine_bytes_dense_ring_per_sweep": st["bytes_dense_ring"],
        "k_sparse": gd.k_sparse,
        "ns_per_edge": round(
            1e9 * dt / (PR100M_ITERS * float(geom["n_edges"])), 2),
        "cache_generation_seconds": round(gen_s, 1),
    })


#: serving-phase geometry: the ALS catalogue matches the als bench
#: scale (4096 users × 16384 items, rank 64), requests are closed-loop
SERVE_ALS_USERS = 4096
SERVE_ALS_ITEMS = 16384
SERVE_ALS_RANK = 64
SERVE_K_TOP = 10
SERVE_MAX_BATCH = 32
SERVE_MAX_DELAY_MS = 2.0
SERVE_REQUESTS = 2048
SERVE_CONCURRENCY = 8


def run_serve_bench(mesh, emit, *, fast: bool = False):
    """The online-serving phase: a closed-loop load generator drives
    the full micro-batching stack (bounded queue → deadline-or-size
    dispatch → one batched predict per micro-batch → scatter) over an
    ALS recommender and an LR scorer, emitting ``serve_als_qps`` and
    ``serve_lr_p99_ms``. SHARED by the bench serve phase and the
    CPU-fallback tier (``fast`` shrinks to unit-test scale) — ``emit``
    receives each line dict so the artifacts can never drift.

    The ALS line also carries the fused-kernel acceptance A/B: batched
    throughput of the fused Pallas matmul+top-k kernel vs the naive
    jnp full-matmul-then-``lax.top_k`` path at the SAME batch geometry
    (``fused_vs_naive_kernel_ratio``). On TPU the fused kernel must
    beat the naive path (the score matrix never round-trips HBM); on
    host backends the kernel only runs in interpret mode, so the ratio
    honestly reads ≪1 and serving itself uses the XLA path — the
    ``note`` field says so.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_distalg import serve as serve_pkg
    from tpu_distalg.ops import pallas_topk as pt
    from tpu_distalg.serve.server import run_closed_loop
    from tpu_distalg.utils import profiling

    on_tpu = next(iter(mesh.devices.flat)).platform == "tpu"
    m, n, rank = ((128, 1024, 16) if fast
                  else (SERVE_ALS_USERS, SERVE_ALS_ITEMS,
                        SERVE_ALS_RANK))
    n_requests = 96 if fast else SERVE_REQUESTS
    max_batch = 8 if fast else SERVE_MAX_BATCH
    rng = np.random.default_rng(0)
    U = rng.normal(size=(m, rank)).astype(np.float32)
    V = rng.normal(size=(n, rank)).astype(np.float32)
    cfg = serve_pkg.ServeConfig(
        max_batch=max_batch, max_delay_ms=SERVE_MAX_DELAY_MS,
        queue_depth=max(128, 4 * max_batch), k_top=SERVE_K_TOP)

    # --- the fused-vs-naive kernel A/B at the serving batch geometry
    Qb = jnp.asarray(U[rng.integers(0, m, size=max_batch)])
    Vd = jnp.asarray(V)
    blk = 256 if fast else 1024
    fused_rate, _ = profiling.steps_per_sec(
        lambda: pt.fused_matmul_topk(Qb, Vd, 0, n, k=SERVE_K_TOP,
                                     block_items=blk,
                                     interpret=not on_tpu),
        steps=1, repeats=2, with_stats=True)
    naive_rate, _ = profiling.steps_per_sec(
        lambda: pt.xla_matmul_topk(Qb, Vd, 0, n, k=SERVE_K_TOP),
        steps=1, repeats=2, with_stats=True)
    kernel_ratio = round(fused_rate / naive_rate, 3) if naive_rate \
        else None

    # --- ALS serving: one server per model so the latency percentiles
    #     are the model's own
    als_srv = serve_pkg.Server(mesh, cfg)
    try:
        model = als_srv.add_model(serve_pkg.als_model(
            U, V, mesh, k_top=SERVE_K_TOP, name="als"))
        payloads = [np.int32(int(v))
                    for v in rng.integers(0, m, size=n_requests)]
        _, info = run_closed_loop(als_srv, "als", payloads,
                                  concurrency=SERVE_CONCURRENCY,
                                  retries=2)
        s = als_srv.emit_counters()
    finally:
        als_srv.close()
    if info["ok"] == 0:
        # a dead server must fail the phase loudly, not emit qps=0 /
        # p99=0 lines — a 0.0 latency artifact would read as PERFECT
        # to the lower-is-better tripwire and the ceiling claim, and
        # would poison the reference for every later round
        raise RuntimeError(
            f"serve bench: all {n_requests} ALS requests failed "
            f"({info['failed']} failed after retries)")
    emit({
        "metric": "serve_als_qps",
        "value": info["qps"],
        "unit": "req/s",
        "vs_baseline": None,
        "p50_ms": s["p50_ms"], "p99_ms": s["p99_ms"],
        "n_requests": n_requests, "ok": info["ok"],
        "shed": s["shed"], "batches": s["batches"],
        "mean_batch_fill": s["models"]["als"]["mean_batch_fill"],
        "max_batch": max_batch, "max_delay_ms": SERVE_MAX_DELAY_MS,
        "concurrency": SERVE_CONCURRENCY,
        "k_top": SERVE_K_TOP, "n_items": n, "n_users": m, "rank": rank,
        "merge": model.meta["merge"], "n_model": model.meta["n_model"],
        "fused_predictor": model.meta["fused"],
        "fused_vs_naive_kernel_ratio": kernel_ratio,
        "kernel_fused_batches_per_sec": round(fused_rate, 2),
        "kernel_naive_batches_per_sec": round(naive_rate, 2),
        "degraded_geometry": fast,
        **({} if on_tpu else {
            "note": "host backend: the Pallas kernel runs in interpret "
                    "mode (ratio honestly <1) and serving uses the XLA "
                    "top-k path; the >=1x fused claim needs the TPU "
                    "backend"}),
    })

    # --- LR serving (latency headline: p99 of the scoring path)
    lr_srv = serve_pkg.Server(mesh, cfg)
    try:
        w = rng.normal(size=(N_FEATURES + 1,)).astype(np.float32)
        lr_srv.add_model(serve_pkg.lr_model(w, name="lr"))
        lr_payloads = list(rng.normal(
            size=(n_requests, N_FEATURES + 1)).astype(np.float32))
        _, lr_info = run_closed_loop(lr_srv, "lr", lr_payloads,
                                     concurrency=SERVE_CONCURRENCY,
                                     retries=2)
        ls = lr_srv.emit_counters()
    finally:
        lr_srv.close()
    if lr_info["ok"] == 0:
        raise RuntimeError(
            f"serve bench: all {n_requests} LR requests failed "
            f"({lr_info['failed']} failed after retries)")
    emit({
        "metric": "serve_lr_p99_ms",
        "value": ls["p99_ms"],
        "unit": "ms",
        "vs_baseline": None,
        "lower_is_better": True,
        "qps": lr_info["qps"], "p50_ms": ls["p50_ms"],
        "n_requests": n_requests, "ok": lr_info["ok"],
        "shed": ls["shed"], "batches": ls["batches"],
        "d": N_FEATURES + 1, "max_batch": max_batch,
        "max_delay_ms": SERVE_MAX_DELAY_MS,
        "concurrency": SERVE_CONCURRENCY,
        "degraded_geometry": fast,
    })


def _bench_serve(mesh, n_chips):
    """The online-serving phase — see :func:`run_serve_bench`."""
    run_serve_bench(mesh, _emit)


def _bench_als(mesh, n_chips):
    """ALS at a scale the reference's broadcast-everything design cannot
    reach: it re-broadcasts the FULL dense R, U, V to every task each
    half-sweep (``matrix_decomposition.py:46-48``) — at 4096×16384 that
    is ~256 MB per task per half-sweep over TCP. Here R stays resident
    in HBM, solves are batched Cholesky on the MXU, and V shards over
    the model axis when one exists."""
    import jax
    import jax.numpy as jnp

    from tpu_distalg.models import als
    from tpu_distalg.utils import profiling, prng

    # 50 sweeps per timed call: at ~2 ms/sweep a 10-sweep call is
    # ~20 ms of device time — the tunnel round-trip would dominate and
    # under-report by 3-4x (measured 119-176 vs ~500 device-side)
    m, n, k, sweeps = 4096, 16384, 64, 50
    cfg = als.ALSConfig(m=m, n=n, k=k, lam=0.0, n_iterations=sweeps)
    key = prng.root_key(cfg.seed)
    U0 = jax.random.normal(jax.random.fold_in(key, 0), (m, k)) * 0.3
    V0 = jax.random.normal(jax.random.fold_in(key, 1), (n, k)) * 0.3
    R = U0 @ V0.T  # exactly rank-k, as the reference synthesizes (:42)
    Ui = jax.random.normal(jax.random.fold_in(key, 2), (m, k)) * 0.1
    Vi = jax.random.normal(jax.random.fold_in(key, 3), (n, k)) * 0.1
    fn = als.make_fit_fn(mesh, cfg)
    best, spread, (_, _, errs) = profiling.steps_per_sec(
        lambda: fn(R, Ui, Vi), steps=sweeps, with_stats=True,
        with_output=True, repeats=N_REPEATS, chain=8)

    # measured baseline stand-in: the reference runs one Spark job per
    # half-sweep, re-broadcasting the full dense R/U/V each time
    # (matrix_decomposition.py:46-48); the driver shape here is a
    # 1-sweep jit call + host round-trip per sweep
    import numpy as np

    one_fn = als.make_fit_fn(
        mesh, als.ALSConfig(m=m, n=n, k=k, lam=0.0, n_iterations=1))
    state = {"u": Ui, "v": Vi}

    def one_iter():
        u2, v2, _ = one_fn(R, state["u"], state["v"])
        state["u"] = jnp.asarray(np.asarray(u2))
        state["v"] = jnp.asarray(np.asarray(v2))

    measured_baseline = _measured_driver_baseline(one_iter)
    denom, floor = _floor_denominator(measured_baseline, best)

    _emit({
        "metric": "als_4kx16k_sweeps_per_sec_per_chip",
        "value": round(best / n_chips, 3),
        "unit": "sweeps/s/chip",
        "vs_baseline": round(best / n_chips / denom, 2),
        "baseline_sweeps_per_sec_measured": round(measured_baseline, 3),
        "baseline_floor_sweeps_per_sec": round(floor, 3),
        "baseline_method": "jit-per-sweep host-roundtrip loop "
                           "(measured, the reference's job-per-half-"
                           "sweep driver shape minus Spark overheads); "
                           "vs_baseline divides by max(measured, floor) "
                           "where floor = an idealized Spark driver at "
                           f"{ASSUMED_SPARK_JOBS_PER_SEC} jobs/s paying "
                           "the same per-sweep device compute",
        "m": m, "n": n, "k": k,
        "final_rmse": round(float(jnp.asarray(errs)[-1]), 6),
        "spread": spread,
    })

    # ---- the HARD instance (r4 verdict #7): ridge-regularized solve
    # (lam>0 — the reference's distinguishing feature,
    # matrix_decomposition.py:30-31) on a NOISY R that is not exactly
    # rank-k, converged by RMSE plateau rather than exact recovery ----
    sigma = 0.1
    cfg_n = als.ALSConfig(m=m, n=n, k=k, lam=0.01, n_iterations=sweeps)
    Rn = R + sigma * jax.random.normal(
        jax.random.fold_in(key, 9), (m, n))
    fn_n = als.make_fit_fn(mesh, cfg_n)
    best_n, spread_n, (_, _, errs_n) = profiling.steps_per_sec(
        lambda: fn_n(Rn, Ui, Vi), steps=sweeps, with_stats=True,
        with_output=True, repeats=N_REPEATS, chain=8)
    e = np.asarray(errs_n)
    final = float(e[-1])
    # never empty: e[-1] == final always satisfies the threshold
    within = np.flatnonzero(e <= final * 1.05)
    denom_n, floor_n = _floor_denominator(measured_baseline, best_n)
    _emit({
        "metric": "als_4kx16k_noisy_ridge_sweeps_per_sec_per_chip",
        "value": round(best_n / n_chips, 3),
        "unit": "sweeps/s/chip",
        "vs_baseline": round(best_n / n_chips / denom_n, 2),
        "baseline_floor_sweeps_per_sec": round(floor_n, 3),
        "baseline_note": "same measured driver baseline as the exact-"
                         "recovery line (identical per-sweep compute)",
        "m": m, "n": n, "k": k, "lam": cfg_n.lam, "noise_sigma": sigma,
        "final_rmse": round(final, 6),
        "rmse_floor_note": "best achievable rmse ~= sigma for "
                           "k << min(m,n); converged means plateauing "
                           "there, not recovering rank-k exactly",
        "sweeps_to_within_5pct_of_final": int(within[0]) + 1,
        "spread": spread_n,
    })


def _bench_ring_attention(mesh, n_chips):
    """Long-context headroom evidence on real hardware (SURVEY.md §5
    charter; the reference has no attention). Three metric lines:
    32k-token causal flash FORWARD (vs the measured XLA online-softmax
    path as its baseline), 32k fwd+bwd through the Pallas backward
    kernels (training rate — the XLA backward OOMs at this length, see
    ops/pallas_attention.py), and the 128k-token single-chip forward
    (previously a README-only claim). On one chip the ring is a single
    hop — the multi-chip collective path is exercised on the CPU mesh
    (tests/test_ring.py) and in the multichip dryrun. Every spread is
    expressed in the metric's own unit."""
    import functools

    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from tpu_distalg.parallel import DATA_AXIS, data_parallel
    from tpu_distalg.parallel.ring import ring_attention
    from tpu_distalg.utils import profiling, prng

    H, d = 8, 128
    key = prng.root_key(0)

    def qkv(S):
        return tuple(
            jax.random.normal(jax.random.fold_in(key, i), (S, H, d),
                              jnp.bfloat16)
            for i in range(3)
        )

    # ---- 32k forward: flash vs the XLA online-softmax path ----
    # SCAN-WRAPPED (r4 weak #4): a single 32k forward is only ~20 ms of
    # device time, so even chain=4 charged ~25 ms of tunnel round-trip
    # per call — the recorded "46 TFLOP/s at 32k vs 109 at 128k" gap
    # was mostly measurement residue, not kernel inefficiency. Each
    # timed call now runs n_inner forwards inside one jitted lax.scan
    # (the output feeds the next iteration's query, so nothing folds
    # away), which is also the shape a training loop runs the kernel in.
    def chained_fwd(n_inner, **kw):
        # k/v are ARGS, not closure captures: captured 16-64 MB arrays
        # become jit constants that upload to the remote compiler at
        # tunnel speed (minutes at 128k)
        f = data_parallel(
            functools.partial(ring_attention, causal=True, **kw),
            mesh,
            in_specs=(P(DATA_AXIS, None, None),) * 3,
            out_specs=P(DATA_AXIS, None, None),
        )

        def run(qq, kc, vc):
            def body(qc, _):
                return f(qc, kc, vc).astype(jnp.bfloat16), None

            return jax.lax.scan(body, qq, None, length=n_inner)[0]

        return jax.jit(run)

    S = 32768
    q, kk, v = qkv(S)
    N_INNER = 16
    flash_fwd = chained_fwd(N_INNER, use_flash=True)
    xla_fwd = chained_fwd(4, kv_chunk=2048)
    flops = S * S / 2 * d * H * 2 * 2  # causal: S^2/2 keys avg, 2 matmuls
    best, spread = profiling.steps_per_sec(
        lambda: flash_fwd(q, kk, v), steps=N_INNER,
        with_stats=True, repeats=N_REPEATS, chain=8)
    xla_best, _ = profiling.steps_per_sec(
        lambda: xla_fwd(q, kk, v), steps=4,
        with_stats=True, repeats=N_REPEATS, chain=4)
    _emit({
        "metric": "ring_attention_32k_tokens_per_sec_per_chip",
        "value": round(S * best / n_chips, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(best / xla_best, 2),
        "baseline_tokens_per_sec_measured": round(
            S * xla_best / n_chips, 1),
        "baseline_method": "the XLA online-softmax ring path "
                           "(kv_chunk=2048), measured same shapes",
        "seq_len": S, "heads": H, "head_dim": d, "kernel": "flash",
        "causal": True,
        "achieved_tflops": round(flops * best / n_chips / 1e12, 2),
        "timing": f"{N_INNER} forwards per jitted scan, chain=8 "
                  "(r4's 46-vs-109 TFLOP/s 32k/128k gap was tunnel "
                  "round-trip residue on ~20 ms calls)",
        "spread": _scale_spread(spread, S / n_chips),
    })

    # ---- 32k forward+backward: training at flash speed ----
    # scan-wrapped like the forward: n_inner grad steps per jitted call
    # (the dq cotangent feeds a zero-weighted update of the carried q,
    # so every iteration depends on the previous gradient)
    def chained_grad(n_inner, **kw):
        f = data_parallel(
            functools.partial(ring_attention, causal=True, **kw),
            mesh,
            in_specs=(P(DATA_AXIS, None, None),) * 3,
            out_specs=P(DATA_AXIS, None, None),
        )

        def loss(a, b, c):
            return jnp.sum(f(a, b, c).astype(jnp.float32) ** 2)

        grad = jax.grad(loss, argnums=(0, 1, 2))

        def run(qq, kc, vc):
            def body(qc, _):
                # the carry must consume ALL THREE cotangents: with
                # only dq used, XLA dead-code-eliminates the whole
                # dK/dV kernel and the "fwd+bwd" rate silently drops
                # the backward's heavier half (caught: 175 "TFLOP/s"
                # with, 106 fwd-only)
                dq, dk, dv = grad(qc, kc, vc)
                dead = (jnp.sum(dk) + jnp.sum(dv)) * 0.0
                return qc + (dq * 0.0 + dead).astype(qc.dtype), None

            return jax.lax.scan(body, qq, None, length=n_inner)[0]

        return jax.jit(run)

    N_INNER_B = 8
    g = chained_grad(N_INNER_B, use_flash=True)
    b_best, b_spread = profiling.steps_per_sec(
        lambda: g(q, kk, v), steps=N_INNER_B, with_stats=True,
        repeats=N_REPEATS, chain=4)
    fb_flops = flops * 3.5  # fwd + 2.5x bwd (5 tile matmuls vs 2)
    _emit({
        "metric": "ring_attention_32k_fwd_bwd_tokens_per_sec_per_chip",
        "value": round(S * b_best / n_chips, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": None,
        "baseline_note": "the XLA-path backward cannot run at 32k on "
                         "one chip (its vjp saves H*S^2*4 bytes = "
                         "32 GB of probability residuals -> OOM); "
                         "measured 3.2x slower than flash at 8k",
        "seq_len": S, "heads": H, "head_dim": d,
        "kernel": "flash fwd + flash bwd (FlashAttention-2 recompute)",
        "causal": True,
        "achieved_tflops_fwd_bwd": round(
            fb_flops * b_best / n_chips / 1e12, 2),
        "spread": _scale_spread(b_spread, S / n_chips),
    })

    # ---- 128k-token single-chip forward (was README-only) ----
    S128 = 131072
    q, kk, v = qkv(S128)
    flash_fwd_128 = chained_fwd(4, use_flash=True)
    flops128 = S128 * S128 / 2 * d * H * 2 * 2
    l_best, l_spread = profiling.steps_per_sec(
        lambda: flash_fwd_128(q, kk, v), steps=4,
        with_stats=True, repeats=N_REPEATS, chain=2)
    _emit({
        "metric": "ring_attention_128k_tokens_per_sec_per_chip",
        "value": round(S128 * l_best / n_chips, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": None,
        "seq_len": S128, "heads": H, "head_dim": d, "kernel": "flash",
        "causal": True,
        "achieved_tflops": round(flops128 * l_best / n_chips / 1e12, 2),
        "spread": _scale_spread(l_spread, S128 / n_chips),
    })

    # ---- 128k forward+backward: TRAINING at max context, one chip ----
    g128 = chained_grad(2, use_flash=True)
    b128_best, b128_spread = profiling.steps_per_sec(
        lambda: g128(q, kk, v), steps=2, with_stats=True,
        repeats=N_REPEATS, chain=2)
    _emit({
        "metric": "ring_attention_128k_fwd_bwd_tokens_per_sec_per_chip",
        "value": round(S128 * b128_best / n_chips, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": None,
        "baseline_note": "the XLA backward would save H*S^2*4 = 512 GB "
                         "of residuals at this length — impossible on "
                         "any single chip; flash recompute saves "
                         "(O, logsumexp) only",
        "seq_len": S128, "heads": H, "head_dim": d,
        "kernel": "flash fwd + flash bwd (FlashAttention-2 recompute)",
        "causal": True,
        "achieved_tflops_fwd_bwd": round(
            flops128 * 3.5 * b128_best / n_chips / 1e12, 2),
        "spread": _scale_spread(b128_spread, S128 / n_chips),
    })


#: every metric name a full TPU round records — the CPU-fallback tier
#: guarantees a line for EACH of these (measured where CPU-feasible,
#: explicitly skipped-with-zero where the workload needs the TPU), so
#: no round is ever blank again (ROADMAP hygiene rider: r05 recorded
#: zero metrics when the backend never came up)
ALL_METRIC_NAMES = (
    "ssgd_lr_steps_per_sec_per_chip",
    "ssgd_lr_fused_gather_steps_per_sec_per_chip",
    "ssgd_comm_dense_bytes_wire_per_sync",
    "ssgd_comm_bucketed_bytes_wire_per_sync",
    "ssgd_comm_bf16_bytes_wire_per_sync",
    "ssgd_comm_int8_bytes_wire_per_sync",
    "ssgd_comm_topk_bytes_wire_per_sync",
    "ssgd_comm_hier_bytes_wire_per_sync",
    "ssgd_comm_int8_wire_reduction_vs_dense",
    "ssgd_comm_topk_wire_reduction_vs_dense",
    "ssgd_comm_int8_step_speedup",
    "ssgd_comm_topk_step_speedup",
    "ssgd_ssp_straggler_speedup",
    "ssgd_ssp_equal_loss_steps",
    "ssgd_cluster_elastic_speedup",
    "cluster_push_pull_ms",
    "cluster_coordinator_recovery_ms",
    "cluster_wire_reduction_vs_dense",
    "ssgd_lr_100m_rows_steps_per_sec_per_chip",
    "ssgd_lr_1b_rows_virtual_steps_per_sec_per_chip",
    "ssgd_lr_32gb_streamed_steps_per_sec_per_chip",
    "ma_local_sgd_local_steps_per_sec_per_chip",
    "kmeans_10m_iters_per_sec_per_chip",
    "pagerank_1m_iters_per_sec",
    "als_4kx16k_sweeps_per_sec_per_chip",
    "als_4kx16k_noisy_ridge_sweeps_per_sec_per_chip",
    "ring_attention_32k_tokens_per_sec_per_chip",
    "ring_attention_32k_fwd_bwd_tokens_per_sec_per_chip",
    "ring_attention_128k_tokens_per_sec_per_chip",
    "ring_attention_128k_fwd_bwd_tokens_per_sec_per_chip",
    "kmeans_18gb_streamed_steps_per_sec_per_chip",
    "als_17gb_streamed_sweeps_per_sec_per_chip",
    "pagerank_100m_iters_per_sec",
    "serve_als_qps",
    "serve_lr_p99_ms",
    "reshard_1gb_gbps",
    "ssgd_2d_mesh_step_speedup",
    "closure_10m_paths_per_sec",
    "cluster_serve_qps",
    "cluster_serve_p99_under_kill_ms",
    "cluster_serve_availability",
    "cluster_sparse_pull_fraction",
    "pagerank_cluster_iters_per_sec",
    "tuned_step_speedup",
    "cluster_tuned_push_pull_speedup",
)

#: metrics where LOWER is better (latencies; the SSP steps-to-target
#: ratio): the regression tripwire flags these on a >15% RISE, and
#: never flags an improvement
LOWER_IS_BETTER_METRICS = frozenset(("serve_lr_p99_ms",
                                     "ssgd_ssp_equal_loss_steps",
                                     "cluster_push_pull_ms",
                                     "cluster_coordinator_recovery_ms",
                                     "cluster_serve_p99_under_kill_ms",
                                     "cluster_sparse_pull_fraction"))

#: canonical units, for the skipped-with-zero lines
_METRIC_UNITS = {
    "pagerank_1m_iters_per_sec": "iter/s/chip",
    "pagerank_100m_iters_per_sec": "iter/s/chip",
    "kmeans_10m_iters_per_sec_per_chip": "iter/s/chip",
    "ma_local_sgd_local_steps_per_sec_per_chip": "local steps/s/chip",
    "als_4kx16k_sweeps_per_sec_per_chip": "sweeps/s/chip",
    "als_4kx16k_noisy_ridge_sweeps_per_sec_per_chip": "sweeps/s/chip",
    "als_17gb_streamed_sweeps_per_sec_per_chip": "sweeps/s/chip",
    "ssgd_comm_int8_wire_reduction_vs_dense": "x",
    "ssgd_comm_topk_wire_reduction_vs_dense": "x",
    "ssgd_comm_int8_step_speedup": "x",
    "ssgd_comm_topk_step_speedup": "x",
    "ssgd_ssp_straggler_speedup": "x",
    "ssgd_ssp_equal_loss_steps": "x",
    "ssgd_cluster_elastic_speedup": "x",
    "cluster_push_pull_ms": "ms",
    "cluster_coordinator_recovery_ms": "ms",
    "cluster_wire_reduction_vs_dense": "x",
    "ring_attention_32k_tokens_per_sec_per_chip": "tokens/s/chip",
    "ring_attention_32k_fwd_bwd_tokens_per_sec_per_chip":
        "tokens/s/chip",
    "ring_attention_128k_tokens_per_sec_per_chip": "tokens/s/chip",
    "ring_attention_128k_fwd_bwd_tokens_per_sec_per_chip":
        "tokens/s/chip",
    "serve_als_qps": "req/s",
    "serve_lr_p99_ms": "ms",
    "cluster_serve_qps": "req/s",
    "cluster_serve_p99_under_kill_ms": "ms",
    "cluster_serve_availability": "fraction",
    "cluster_sparse_pull_fraction": "fraction",
    "pagerank_cluster_iters_per_sec": "iter/s",
    "reshard_1gb_gbps": "GB/s",
    "ssgd_2d_mesh_step_speedup": "x",
    "closure_10m_paths_per_sec": "paths/s",
    "tuned_step_speedup": "x",
    "cluster_tuned_push_pull_speedup": "x",
}
for _n in ALL_METRIC_NAMES:
    _METRIC_UNITS.setdefault(
        _n, "bytes/sync/shard" if "bytes_wire" in _n
        else "steps/s/chip")


def _cpu_emit(obj):
    """CPU-tier emitter: every line carries the backend tag."""
    _emit({**obj, "backend": "cpu"})


def _emit_missing_as_skipped():
    """A line for every canonical metric the CPU tier could not
    measure: value 0.0 + the skip reason, tagged ``backend: cpu`` —
    parsers see the full metric set, never a blank."""
    with _EMIT_LOCK:
        missing = [n for n in ALL_METRIC_NAMES if n not in _SUMMARY]
    for name in missing:
        _cpu_emit({
            "metric": name,
            "value": 0.0,
            "unit": _METRIC_UNITS[name],
            "vs_baseline": None,
            "skipped": "requires the tpu backend (cpu fallback tier)",
        })


def _run_cpu_fallback(reason: str, fast: bool = False) -> int:
    """The CPU-fallback bench tier (ROADMAP hygiene rider): the axon
    backend never came up, so run every CPU-feasible phase on a
    host-device mesh — honest (degraded-geometry) measurements, each
    line tagged ``backend: cpu`` — and emit explicit skipped-with-zero
    lines for the TPU-only workloads. The artifact records the FULL
    metric set either way; rc stays 2 so the driver still sees the
    backend failure. ``fast=True`` shrinks geometries to unit-test
    scale."""
    global _BACKEND_TAG
    _BACKEND_TAG = "cpu"
    tevents.emit("cpu_fallback", reason=reason)
    print(f"[bench] backend unavailable ({reason}); running the CPU "
          f"fallback tier — all lines tagged backend: cpu",
          file=sys.stderr)

    import jax

    try:
        # the TPU platform never initialised, so the CPU backend can
        # still be selected; more virtual devices would need XLA_FLAGS
        # set before the first backend touch (the driver/conftest does)
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass  # a backend is already live; use whatever it exposes
    from tpu_distalg.parallel import get_mesh

    try:
        devs = jax.devices()
        n_shards = 4 if len(devs) >= 4 else 1
        mesh = get_mesh(data=n_shards, devices=devs[:n_shards])
    except Exception as e:  # noqa: BLE001 — recorded, summary still out
        tevents.emit("cpu_fallback_failed",
                     error=f"{type(e).__name__}: {e}")
        print(f"[bench] cpu fallback could not build a mesh: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
        _emit_summary()
        return 2

    import jax.numpy as jnp

    from tpu_distalg.models import ssgd
    from tpu_distalg.parallel import parallelize
    from tpu_distalg.utils import datasets, profiling

    def cpu_ssgd():
        # the flagship metric on the CPU XLA path: canonical 1M-row
        # geometry unless fast, honest (slow) steps/s
        n_rows = (1 << 14) if fast else N_ROWS
        steps = 5 if fast else 30
        X, y = datasets.synthetic_two_class(n_rows, N_FEATURES, seed=0)
        X = datasets.add_bias_column(X)
        cfg = ssgd.SSGDConfig(n_iterations=steps, eval_test=False)
        Xs, ys = parallelize(X, mesh), parallelize(y, mesh)
        from tpu_distalg.ops import logistic
        from tpu_distalg.utils import prng

        w0 = logistic.init_weights(prng.root_key(7), X.shape[1])
        fn = ssgd.make_train_fn(mesh, cfg, Xs.n_padded)
        ev = (jnp.zeros((1, X.shape[1]), jnp.float32),
              jnp.zeros((1,), jnp.float32))
        best, spread = profiling.steps_per_sec(
            lambda: fn(Xs.data, ys.data, Xs.mask, ev[0], ev[1], w0),
            steps=steps, repeats=1 if fast else 2, with_stats=True)
        _cpu_emit({
            "metric": "ssgd_lr_steps_per_sec_per_chip",
            "value": round(best / n_shards, 2),
            "unit": "steps/s/chip",
            "vs_baseline": None,
            "sampler": "bernoulli", "n_rows": n_rows,
            "degraded_geometry": n_rows != N_ROWS,
            "spread": spread,
        })

    def cpu_pagerank():
        from tpu_distalg.models import pagerank
        from tpu_distalg.ops import graph as gops

        n_v = (1 << 12) if fast else PR_VERTICES
        iters = 3 if fast else 10
        edges = datasets.erdos_renyi_edges(n_v, PR_AVG_DEGREE, seed=0)
        el = gops.prepare_edges(edges, n_v)
        fn = pagerank.make_run_fn(
            mesh, pagerank.PageRankConfig(n_iterations=iters,
                                          mode="standard"), el.n_vertices)
        de = pagerank.prepare_device_edges(el, mesh)
        best, spread = profiling.steps_per_sec(
            lambda: fn(de.src, de.dst, de.w_e, de.emask, de.has_out,
                       de.n_ref),
            steps=iters, repeats=1 if fast else 2, with_stats=True)
        _cpu_emit({
            "metric": "pagerank_1m_iters_per_sec",
            "value": round(best / n_shards, 3),
            "unit": "iter/s/chip",
            "vs_baseline": None,
            "n_vertices": n_v, "n_edges": int(el.n_edges),
            "degraded_geometry": n_v != PR_VERTICES,
            "spread": spread,
        })

    def cpu_pagerank_streamed():
        """The out-of-core engine at an honest degraded geometry: the
        same streamed sweep + sparse combine, just a small power-law
        cache (a CPU host cannot stream 19 GB per iteration in a bench
        window) — the measured line proves the ENGINE runs, the tag
        says the geometry is not the claim's."""
        import tempfile

        from tpu_distalg import graphs
        from tpu_distalg.parallel import comms

        n_v = (1 << 12) if fast else (1 << 18)
        iters = 2 if fast else 3
        cache = os.path.join(tempfile.mkdtemp(prefix="tda_bench_pl_"),
                             "pagerank_pl")
        _, header = graphs.build_powerlaw_block_cache(
            cache, n_vertices=n_v, n_shards=n_shards,
            avg_in_degree=8.0, alpha=PR100M_ALPHA, seed=0,
            block_edges=(1 << 9) if fast else (1 << 14))
        geom = header["geom"]
        gd = graphs.open_graph_dataset(cache, mesh, backend="streamed")
        import jax as _jax

        t0 = time.perf_counter()
        res = graphs.run_streamed_pagerank(
            gd, graphs.StreamedPageRankConfig(n_iterations=iters))
        _jax.block_until_ready(res.ranks)
        dt = time.perf_counter() - t0
        st = comms.rank_combine_stats(gd.k_sparse, gd.n_vertices,
                                      gd.n_shards)
        _cpu_emit({
            "metric": "pagerank_100m_iters_per_sec",
            "value": round(iters / dt / n_shards, 4),
            "unit": "iter/s/chip",
            "vs_baseline": None,
            "n_vertices": int(geom["n_vertices"]),
            "n_edges": int(geom["n_edges"]),
            "edge_bytes_on_disk": int(geom["n_edges"]) * 12,
            "exceeds_one_chip_hbm": False,
            "combine": res.combine,
            "combine_bytes_wire_per_sweep": st["bytes_wire"],
            "combine_bytes_dense_ring_per_sweep": st["bytes_dense_ring"],
            "degraded_geometry": True,
        })

    def cpu_kmeans():
        from tpu_distalg.models import kmeans
        from tpu_distalg.parallel import build_sharded

        n_rows = (1 << 12) if fast else 1 << 20
        k, dim, iters = 8, 16, 3 if fast else 10
        make_rows, _ = datasets.gaussian_mixture_rows(
            k=k, dim=dim, seed=0, spread=8.0)
        cfg = kmeans.KMeansConfig(k=k, n_iterations=iters, seed=0,
                                  init="farthest")
        ps = build_sharded(mesh, n_rows, make_rows)
        c0 = kmeans.init_centers_scaled(make_rows, n_rows, cfg)
        fn = kmeans.make_fit_fn(mesh, cfg)
        best, spread = profiling.steps_per_sec(
            lambda: fn(ps.data, ps.mask, c0),
            steps=iters, repeats=1 if fast else 2, with_stats=True)
        _cpu_emit({
            "metric": "kmeans_10m_iters_per_sec_per_chip",
            "value": round(best / n_shards, 3),
            "unit": "iter/s/chip",
            "vs_baseline": None,
            "n_points": n_rows, "k": k, "dim": dim,
            "degraded_geometry": True,
            "spread": spread,
        })

    def cpu_als():
        import jax as _jax

        from tpu_distalg.models import als
        from tpu_distalg.utils import prng

        m, n, k = ((256, 512, 16) if fast else (1024, 4096, 32))
        sweeps = 2 if fast else 5
        key = prng.root_key(0)
        U0 = _jax.random.normal(_jax.random.fold_in(key, 0), (m, k)) * .3
        V0 = _jax.random.normal(_jax.random.fold_in(key, 1), (n, k)) * .3
        R = U0 @ V0.T
        Ui = _jax.random.normal(_jax.random.fold_in(key, 2), (m, k)) * .1
        Vi = _jax.random.normal(_jax.random.fold_in(key, 3), (n, k)) * .1
        for metric, lam in (
                ("als_4kx16k_sweeps_per_sec_per_chip", 0.0),
                ("als_4kx16k_noisy_ridge_sweeps_per_sec_per_chip", .01)):
            cfg = als.ALSConfig(m=m, n=n, k=k, lam=lam,
                                n_iterations=sweeps)
            fn = als.make_fit_fn(mesh, cfg)
            best, spread = profiling.steps_per_sec(
                lambda: fn(R, Ui, Vi), steps=sweeps,
                repeats=1 if fast else 2, with_stats=True)
            _cpu_emit({
                "metric": metric,
                "value": round(best / n_shards, 3),
                "unit": "sweeps/s/chip",
                "vs_baseline": None,
                "m": m, "n": n, "k": k, "lam": lam,
                "degraded_geometry": True,
                "spread": spread,
            })

    def cpu_local_sgd():
        from tpu_distalg.models import ma

        n_rows = (1 << 12) if fast else 1 << 16
        rounds, n_local = (2, 2) if fast else (5, 5)
        X, y = datasets.synthetic_two_class(n_rows, N_FEATURES, seed=0)
        X = datasets.add_bias_column(X)
        cfg = ma.MAConfig(n_iterations=rounds,
                          n_local_iterations=n_local, eval_test=False)
        from tpu_distalg.models import local_sgd as lsgd
        from tpu_distalg.ops import logistic
        from tpu_distalg.utils import prng

        Xs, ys = parallelize(X, mesh), parallelize(y, mesh)
        fn = lsgd.make_train_fn(mesh, cfg, Xs.n_padded)
        import jax as _jax

        k_init = prng.root_key(cfg.init_seed)
        w0 = logistic.init_weights(_jax.random.fold_in(k_init, 0),
                                   X.shape[1])
        ws0 = _jax.random.uniform(
            _jax.random.fold_in(k_init, 1), (n_shards, X.shape[1]),
            minval=-1.0, maxval=1.0)
        ev = (jnp.zeros((1, X.shape[1]), jnp.float32),
              jnp.zeros((1,), jnp.float32))
        best, spread = profiling.steps_per_sec(
            lambda: fn(Xs.data, ys.data, Xs.mask, ev[0], ev[1], w0,
                       ws0, jnp.zeros((X.shape[1],), jnp.float32)),
            steps=rounds * n_local, repeats=1 if fast else 2,
            with_stats=True)
        _cpu_emit({
            "metric": "ma_local_sgd_local_steps_per_sec_per_chip",
            "value": round(best / n_shards, 2),
            "unit": "local steps/s/chip",
            "vs_baseline": None,
            "sampler": "bernoulli", "n_rows": n_rows,
            "degraded_geometry": True,
            "spread": spread,
        })

    import functools

    _phase_optional("cpu_ssgd", cpu_ssgd)
    _phase_optional(
        "cpu_comm", run_comm_comparison, mesh, _cpu_emit,
        COMM_SCHEDULES, 8 if fast else 300)
    _phase_optional(
        "cpu_comm_speedup",
        functools.partial(
            run_comm_step_speedup, mesh, _cpu_emit,
            **(dict(d=1 << 14, steps=4, repeats=1) if fast else {})))
    _phase_optional(
        "cpu_tuned_step",
        functools.partial(
            run_tuned_step_speedup, mesh, _cpu_emit,
            **(dict(d=1 << 14, steps=4, repeats=1) if fast else {})))
    _phase_optional(
        "cpu_cluster_tuned",
        functools.partial(run_cluster_tuned_push_pull_speedup,
                          _cpu_emit, fast=fast))
    _phase_optional(
        "cpu_ssp",
        functools.partial(
            run_ssp_straggler_speedup, mesh, _cpu_emit,
            **(dict(steps=16, repeats=1, conv_iters=48)
               if fast else {})))
    _phase_optional(
        "cpu_cluster",
        functools.partial(run_cluster_bench, _cpu_emit, fast=fast))
    _phase_optional(
        "cpu_cluster_serve",
        functools.partial(run_cluster_serve_bench, _cpu_emit,
                          fast=fast))
    _phase_optional(
        "cpu_rowstore",
        functools.partial(run_rowstore_bench, _cpu_emit, fast=fast))
    _phase_optional("cpu_pagerank", cpu_pagerank)
    _phase_optional("cpu_pagerank_streamed", cpu_pagerank_streamed)
    _phase_optional(
        "cpu_serve",
        functools.partial(run_serve_bench, mesh, _cpu_emit, fast=fast))
    _phase_optional("cpu_kmeans", cpu_kmeans)
    _phase_optional("cpu_als", cpu_als)
    _phase_optional("cpu_local_sgd", cpu_local_sgd)
    # partition-engine lines at honest degraded geometry (suffixed
    # names + degraded_geometry, so the canonical claim metrics are
    # never fed from a host mesh); both raise-don't-fabricate
    _phase_optional(
        "cpu_reshard",
        functools.partial(run_reshard_bench, mesh, _cpu_emit,
                          payload_gb=0.016 if fast else 0.25,
                          repeats=1 if fast else 2))
    if not fast:
        # two extra compile arms — too heavy for the in-process fast
        # unit-test mode; the real fallback round still records it
        _phase_optional(
            "cpu_mesh2d",
            functools.partial(run_mesh2d_bench, mesh, _cpu_emit,
                              d=2048, rows_per_dev=128, steps=8,
                              repeats=1))
        _phase_optional(
            "cpu_closure",
            functools.partial(run_closure_bench, mesh, _cpu_emit,
                              V=350, deg=6, min_paths=0))
    _emit_missing_as_skipped()
    _emit_summary()
    return 2


def main(argv=None):
    import argparse

    parser = argparse.ArgumentParser(prog="bench")
    parser.add_argument("--profile", type=str, default=None, metavar="DIR",
                        help="capture a jax.profiler device trace of the "
                             "benchmarked runs into DIR")
    parser.add_argument("--telemetry-dir", type=str, default=None,
                        metavar="DIR",
                        help="write structured JSONL runtime events "
                             "(phases, heartbeats, stalls, backend-init "
                             "attempts, every metric) into DIR; "
                             "$TDA_TELEMETRY_DIR is the default; "
                             "summarize with 'tda report DIR'")
    parser.add_argument("--fault-plan", type=str, default=None,
                        metavar="SPEC",
                        help="deterministic fault-injection plan "
                             "(tpu_distalg/faults/): bench the recovery "
                             "machinery's overhead under a replayable "
                             "fault schedule; $TDA_FAULT_PLAN is the "
                             "default")
    parser.add_argument("--comm", default="dense", metavar="SCHED",
                        help="gradient-sync schedule for the flagship "
                             "SSGD phase (parallel/comms.py): dense "
                             "(default), bucketed, hier, bf16, int8, "
                             "topk[:frac]. The comm-comparison phase "
                             "records all schedules regardless")
    parser.add_argument("--sync", default="bsp", metavar="MODE",
                        help="staleness bound for the ssp phase "
                             "(parallel/ssp.py): 'bsp' measures at the "
                             "canonical bound, 'ssp:s' overrides it — "
                             "the BSP-vs-SSP straggler A/B runs either "
                             "way; off-default bounds emit under "
                             "_boundN-suffixed metric names so the "
                             "canonical claim metric is never "
                             "overwritten")
    args = parser.parse_args(argv)

    tevents.configure(args.telemetry_dir)
    from tpu_distalg import faults as tfaults

    tfaults.configure(args.fault_plan)
    # phase-stall watchdog: replaces the absolute-timer _watchdog thread
    # (and fixes its summary/print race by construction — one lock)
    hb = theartbeat.Heartbeat(
        interval=min(60.0, max(0.25, WATCHDOG_SECONDS / 4)),
        stall_after=WATCHDOG_SECONDS, on_stall=_watchdog_fire)
    hb.start()
    threading.Thread(target=_hard_deadline_loop, daemon=True,
                     name="bench-hard-deadline").start()
    try:
        return _run(args)
    finally:
        hb.stop()


def _run(args):
    from tpu_distalg.parallel import get_mesh

    # a tunneled TPU backend can be transiently UNAVAILABLE (observed:
    # ~tens of minutes) or HANG outright (observed: ~26 min, round 5);
    # the supervisor runs each attempt under a deadline, retries with
    # the fixed 60 s schedule (cap == base), records every attempt as
    # telemetry events, and raises instead of dying with no artifact.
    # The retry COUNT is capped by the REMAINING hard-deadline budget
    # (r5 regression: 40 fixed attempts x 6 min = 4 h of retrying
    # inside a 3 h window — the driver's rc-124 SIGKILL landed while
    # init was still spinning and the artifact parsed null); half the
    # remaining window is left for the bench proper.
    # the rig's measured backend-init time (from the newest RigProfile,
    # when `tda tune` has recorded one) re-prices both the per-attempt
    # deadline and the retry count — r05 spent 26 min retrying against
    # the worst-case cap on a rig whose healthy init takes seconds
    rig_prof = _rig_profile()
    init_s = ((rig_prof or {}).get("measurements")
              or {}).get("backend_init_s")
    budget_retries = _init_retry_budget(
        HARD_DEADLINE_SECONDS - (time.monotonic() - _T0),
        init_seconds=init_s)
    try:
        mesh = tsupervisor.init_backend(
            timeout=_init_attempt_timeout(init_s),
            retries=budget_retries,
            backoff=INIT_RETRY_SECONDS,
            backoff_cap=INIT_RETRY_SECONDS,
            init_fn=get_mesh)
    except tsupervisor.BackendUnavailableError as e:
        # the CPU-fallback tier (ROADMAP hygiene rider): r05 recorded
        # ZERO metrics when the backend never came up — now every
        # canonical metric line is emitted, measured on host devices
        # where feasible and skipped-with-zero where not, all tagged
        # backend: cpu
        return _run_cpu_fallback(str(e))
    import jax

    n_chips = len(jax.devices())
    on_tpu = next(iter(mesh.devices.flat)).platform == "tpu"

    from tpu_distalg.utils import profiling

    try:
        with profiling.maybe_trace(args.profile):
            ssgd_per_chip = _phase("ssgd", _bench_ssgd, mesh, on_tpu,
                                   n_chips, args.comm)
            _phase("comm", _bench_comm, mesh, n_chips)
            _phase("comm_speedup", _bench_comm_speedup, mesh, n_chips)
            # the autotuner's end-to-end A/B: raises (recorded) when
            # the resolver mispredicts, never emits a sub-1.0 value
            # under the floor-claimed metric
            _phase_optional("tuned_step", _bench_tuned_step, mesh,
                            n_chips)
            # optional: run_ssp_straggler_speedup raises rather than
            # emitting a fabricated 0.0 ratio when SSP misses the band
            _phase_optional("ssp", _bench_ssp, mesh, n_chips,
                            args.sync)
            # the multi-process elastic runtime: host processes by
            # construction, so it runs (honestly) on every backend;
            # raises rather than fabricating on an incomplete run
            _phase_optional("cluster", _bench_cluster, mesh, n_chips)
            # the cluster-tier autotuner A/B (host wire, so it runs
            # honestly on every backend)
            _phase_optional("cluster_tuned", _bench_cluster_tuned,
                            mesh, n_chips)
            # the serving plane rides the same host-thread honesty;
            # raises on an unfired kill or a bitwise divergence
            _phase_optional("cluster_serve", _bench_cluster_serve,
                            mesh, n_chips)
            # the sharded row store: host numpy + wire frames, honest
            # everywhere; raises on an incomplete run, a broken rank
            # invariant, or pulls that turn out dense
            _phase_optional("rowstore", _bench_rowstore, mesh, n_chips)
            # optional, and BOTH raise instead of emitting fabricated
            # lines on failure (the serve-round-3 / ssp lesson): a
            # parity miss or a refused capacity is a recorded phase
            # error, never a 0.0 that poisons the tripwire reference
            _phase_optional("reshard", _bench_reshard, mesh, n_chips)
            _phase_optional("mesh2d", _bench_mesh2d, mesh, n_chips)
            _phase_optional("closure", _bench_closure, mesh, n_chips)
            if on_tpu:
                _phase("ssgd_100m", _bench_ssgd_scale, mesh, n_chips)
                _phase("ssgd_1b_virtual", _bench_ssgd_virtual, mesh,
                       n_chips)
                _phase("ssgd_32gb_stream", _bench_ssgd_stream, mesh,
                       n_chips)
                _phase("local_sgd", _bench_local_sgd, mesh, n_chips,
                       ssgd_per_chip)
                _phase("kmeans_10m", _bench_kmeans_scale, mesh, n_chips)
            _phase("pagerank", _bench_pagerank, mesh, n_chips)
            # optional: a serving failure is recorded (and the ok==0
            # guard in run_serve_bench raises rather than emitting a
            # perfect-looking 0.0 latency) without sinking als/ring
            _phase_optional("serve", _bench_serve, mesh, n_chips)
            if on_tpu:
                _phase("als", _bench_als, mesh, n_chips)
                _phase("ring_attention", _bench_ring_attention, mesh,
                       n_chips)
                # the >HBM data-subsystem lines LAST (multi-GB cache
                # builds; a full disk must not sink the lines above)
                _phase_optional("kmeans_18gb_stream",
                                _bench_kmeans_streamed, mesh, n_chips)
                _phase_optional("als_17gb_stream",
                                _bench_als_streamed, mesh, n_chips)
                _phase_optional("pagerank_100m_stream",
                                _bench_pagerank_streamed, mesh,
                                n_chips)
    finally:
        # even a partial run's metrics survive in the tail
        _emit_summary()


if __name__ == "__main__":
    import sys

    sys.exit(main())
